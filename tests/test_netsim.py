"""netsim cluster-protocol models (ISSUE 15 tentpole): the REAL shipped
cluster code — ``ClusterDoor.route``/``route_recheck``/``migrate_key``
(the move guard), ``ClusterClient``'s MOVED/ASK chase and scatter/
gather demux, ``supervisor.migrate_slot`` (the live-resharding pump),
``wireutil.exchange``, and ``resp.consume_one_shot_licenses`` — driven
over a simulated network under the schedule explorer, so the
delivery × fault × crash interleavings are ENUMERATED, not sampled.

Every failing schedule prints an ``RTPU_SCHEDULE_REPLAY`` token that
replays it exactly.  The mutation guards revert the historical fixes
(the ``route_recheck`` presence re-check, the MOVED one-retry budget,
the one-shot ASKING burn, the pooled-socket drop-on-OSError
discipline) and assert the models CATCH them with a replayable token.

The node harness (:class:`MiniClusterNode`) is deliberately thin: a
dict store + the real door/slotmap/license code, wired through
``wireutil``'s server-side framing.  Everything protocol-bearing is
the shipped code (the netsim transport-seam contract,
docs/static_analysis.md).
"""

import threading
import time
import types

import pytest

from redisson_tpu.analysis import explorer, netsim
from redisson_tpu.analysis.explorer import (
    ScheduleFailure,
    checkpoint,
    explore,
    schedule_test,
)
from redisson_tpu.cluster import supervisor as supervisor_mod
from redisson_tpu.cluster.client import ClusterClient, ClusterError
from redisson_tpu.cluster.door import ClusterDoor
from redisson_tpu.cluster.slotmap import SlotMap
from redisson_tpu.cluster.slots import NSLOTS, key_slot
from redisson_tpu.serve import resp as resp_mod
from redisson_tpu.serve.wireutil import (
    ReplyError,
    decode_command,
    encode_reply,
    exchange,
)

pytestmark = pytest.mark.netsim


@pytest.fixture(autouse=True)
def _unpatch_network():
    """A failing schedule abandons the explored body mid-``with Net()``
    (its __exit__ never runs), which would leave every LATER test in
    this process dialing the sim and getting ConnectionRefusedError."""
    yield
    netsim.restore_patches()


KEY = b"k"
SLOT = key_slot(KEY)

ADDR_A = ("node-a", 7001)
ADDR_B = ("node-b", 7002)


def _topology(a_slots, b_slots):
    return {"nodes": [
        {"id": "A", "host": ADDR_A[0], "port": ADDR_A[1],
         "slots": a_slots},
        {"id": "B", "host": ADDR_B[0], "port": ADDR_B[1],
         "slots": b_slots},
    ]}


# ---------------------------------------------------------------------------
# the node harness (thin: real door + real license burn over a dict store)
# ---------------------------------------------------------------------------


class _KeysShim:
    """The keyspace surface ``ClusterDoor`` uses (dump/delete/ttl)."""

    def __init__(self, node):
        self._node = node

    def get_keys(self):
        return list(self._node.store)

    def delete(self, name):
        self._node.store.pop(name, None)

    def remain_time_to_live(self, name):
        return -1


class MiniClusterNode:
    """One simulated cluster node: dict store + REAL ClusterDoor."""

    _DUMP_MAGIC = b"MDMP"

    def __init__(self, net, addr, myid, topo, slow_first_get_s=0.0):
        self.host, self.port = addr
        self.addr = addr
        self.store: dict = {}
        self.slotmap = SlotMap.from_dict(topo)
        self.door = ClusterDoor(self, self.slotmap, myid, announce=addr)
        self.counts: dict = {}
        self._keys = _KeysShim(self)
        self._client = types.SimpleNamespace(get_keys=lambda: self._keys)
        self._slow_first_get_s = slow_first_get_s
        self._slowed = False
        net.listen(addr, self.serve, name=myid)

    # -- the surface the REAL door calls back into --------------------------

    def _exists_any(self, name: str) -> bool:
        return name in self.store

    def _dump_payload(self, name: str):
        v = self.store.get(name)
        return None if v is None else self._DUMP_MAGIC + v

    # -- wire loop ----------------------------------------------------------

    def serve(self, sock, peer) -> None:
        ctx = types.SimpleNamespace(asking=False, trace_next=None)
        buf = b""
        pos = 0
        while True:
            chunk = sock.recv(1 << 16)
            if not chunk:
                return
            buf += chunk
            while True:
                try:
                    cmd, end = decode_command(buf, pos)
                except (IndexError, ValueError):
                    break
                pos = end
                sock.sendall(self.dispatch(cmd, ctx))

    # -- dispatch (mirrors RespServer._dispatch's cluster slice) ------------

    def dispatch(self, cmd, ctx) -> bytes:
        name = cmd[0].decode("latin-1", "replace").upper()
        self.counts[name] = self.counts.get(name, 0) + 1
        try:
            if name == "ASKING":
                ctx.asking = True
                return b"+OK\r\n"
            if name == "CLUSTER":
                return self._cluster(cmd)
            frame, guarded = self.door.route(name, cmd, ctx)
            if frame is not None:
                return frame
            if guarded:
                with self.door.move_lock:
                    frame = self.door.route_recheck(name, cmd)
                    if frame is not None:
                        return frame
                    return self._execute(name, cmd)
            return self._execute(name, cmd)
        except Exception as e:  # noqa: BLE001 - the -ERR contract
            return encode_reply(ReplyError(f"ERR {e}"))
        finally:
            # The REAL one-shot license discipline (serve/resp.py): a
            # keyless command between ASKING and its redirected command
            # must burn the license.
            resp_mod.consume_one_shot_licenses(ctx, name)

    def _execute(self, name: str, cmd) -> bytes:
        if name == "PING":
            return b"+PONG\r\n"
        if name == "SET":
            self.store[cmd[1].decode()] = cmd[2]
            return b"+OK\r\n"
        if name == "GET":
            if self._slow_first_get_s and not self._slowed:
                # One slow reply: the cross-command desync trap the
                # pooled-socket drop discipline exists for.
                self._slowed = True
                time.sleep(self._slow_first_get_s)
            return encode_reply(self.store.get(cmd[1].decode()))
        if name == "DEL":
            n = 0
            for k in cmd[1:]:
                n += 1 if self.store.pop(k.decode(), None) is not None \
                    else 0
            return encode_reply(n)
        if name == "EXISTS":
            return encode_reply(
                sum(1 for k in cmd[1:] if k.decode() in self.store)
            )
        if name == "RESTORE":
            blob = cmd[3]
            if not blob.startswith(self._DUMP_MAGIC):
                return encode_reply(ReplyError("ERR bad dump payload"))
            self.store[cmd[1].decode()] = blob[len(self._DUMP_MAGIC):]
            return b"+OK\r\n"
        if name == "MIGRATE":
            r = self.door.migrate_key(
                cmd[1].decode(), int(cmd[2]), cmd[3], int(cmd[5])
            )
            return encode_reply(r)
        return encode_reply(ReplyError(f"ERR unknown command '{name}'"))

    def _cluster(self, cmd) -> bytes:
        sub = cmd[1].decode("latin-1", "replace").upper()
        if sub == "MYID":
            return encode_reply(self.door.myid.encode())
        if sub == "SLOTS":
            return encode_reply([
                [start, end, [host.encode(), port, nid.encode()]]
                for start, end, nid, host, port
                in self.slotmap.slots_table()
            ])
        if sub == "SETSLOT":
            slot = int(cmd[2])
            mode = cmd[3].decode().upper()
            if mode == "IMPORTING":
                self.slotmap.set_importing(slot, cmd[4].decode())
            elif mode == "MIGRATING":
                self.slotmap.set_migrating(slot, cmd[4].decode())
            elif mode == "NODE":
                self.slotmap.set_owner(slot, cmd[4].decode())
            elif mode == "STABLE":
                self.slotmap.set_stable(slot)
            else:
                return encode_reply(ReplyError("ERR bad SETSLOT"))
            return b"+OK\r\n"
        if sub == "GETKEYSINSLOT":
            return encode_reply([
                k.encode()
                for k in self.door.keys_in_slot(int(cmd[2]), int(cmd[3]))
            ])
        if sub == "COUNTKEYSINSLOT":
            return encode_reply(len(self.door.keys_in_slot(int(cmd[2]))))
        if sub == "MIGRATABLE":
            return encode_reply([
                k.encode()
                for k in self.door.undumpable_in_slot(int(cmd[2]))
            ])
        return encode_reply(ReplyError(f"ERR unknown CLUSTER {sub}"))


def _client(*seeds, timeout_s=30.0) -> ClusterClient:
    # deadnode_attempts=0: these models probe single-attempt semantics
    # (a timeout must SURFACE, not ride the failover retry loop — the
    # desync model's OSError contract).  The retry-through-takeover
    # behavior is modeled in tests/test_netsim_failover.py instead.
    c = ClusterClient(list(seeds), timeout_s=timeout_s,
                      deadnode_attempts=0)
    # The executor seam (netsim transport-seam contract): scatter legs
    # on SIMULATED threads, so leg delivery order is explored.
    c._pool = netsim.SimThreadExecutor()
    return c


# ---------------------------------------------------------------------------
# model 1: live slot migration under concurrent acked writes
# ---------------------------------------------------------------------------


def _write_retrying(client, val, attempts=60):
    """One acked write, retried through drops/crashes (idempotent SET:
    un-acked attempts are unconstrained, the ACK is the contract)."""
    for _ in range(attempts):
        try:
            r = client.execute(b"SET", KEY, val)
        except (OSError, ClusterError):
            time.sleep(0.05)  # virtual: let the fault window pass
            continue
        except ReplyError as e:
            if e.code in ("TRYAGAIN", "CLUSTERDOWN"):
                time.sleep(0.05)
                continue
            raise
        assert r == b"OK"
        return True
    raise AssertionError("write never acked within the retry budget")


def _migration_body(drop_budget=0, writes=2, wait_for_migrating=False):
    """A writer keeps SETting a key in SLOT while the REAL migrate_slot
    pump moves that slot A -> B.  Invariant, in EVERY schedule: after
    the pump finishes, the last ACKED value is what a read returns —
    zero acked-write loss across the migrated slot.

    ``wait_for_migrating`` gates the writer until the source shows the
    slot MIGRATING, focusing the search on the route-vs-move-guard
    window (the mutation hunts need the write to land mid-handoff)."""
    with netsim.Net(drop_budget=drop_budget) as net:
        topo = _topology([[0, NSLOTS - 1]], [])
        na = MiniClusterNode(net, ADDR_A, "A", topo)
        nb = MiniClusterNode(net, ADDR_B, "B", topo)
        na.store[KEY.decode()] = b"0"
        client = _client(ADDR_A, ADDR_B)
        acked = [b"0"]

        def writer():
            if wait_for_migrating:
                while True:
                    d = na.slotmap.lookup(SLOT)
                    if d.migrating_to is not None or d.owner != "A":
                        break
                    time.sleep(0.01)  # virtual
            for i in range(1, writes + 1):
                val = b"%d" % i
                _write_retrying(client, val)
                acked.append(val)

        def pump():
            # The driver is resumable by design (every step idempotent,
            # per-key atomicity lives in the source's move guard): a
            # dropped control connection re-runs the pump.
            for _ in range(4):
                try:
                    moved = supervisor_mod.migrate_slot(
                        SLOT, ADDR_A, ADDR_B,
                        notify=(ADDR_A, ADDR_B), batch=4,
                    )
                except (OSError, RuntimeError):
                    time.sleep(0.05)  # virtual
                    continue
                assert moved >= 0
                return
            raise AssertionError("pump never completed")

        wt = threading.Thread(target=writer)
        pt = threading.Thread(target=pump)
        wt.start()
        pt.start()
        wt.join()
        pt.join()
        assert na.slotmap.owner(SLOT) == "B"
        assert nb.slotmap.owner(SLOT) == "B"
        final = client.execute(b"GET", KEY)
        assert final == acked[-1], (
            f"acked write lost across the migration: read {final!r}, "
            f"last acked {acked[-1]!r}"
        )
        client.close()


@schedule_test(max_schedules=60, random_schedules=32, preemption_bound=2,
               max_steps=200000)
def test_model_migration_no_acked_write_lost():
    _migration_body()


@schedule_test(max_schedules=30, random_schedules=16, preemption_bound=1,
               max_steps=200000)
def test_model_migration_survives_connection_drops():
    _migration_body(drop_budget=1, writes=1)


@schedule_test(max_schedules=200, random_schedules=64, preemption_bound=2,
               max_steps=200000)
def test_model_migration_write_lands_mid_handoff():
    """The focused variant the mutation guard hunts on: the write is
    gated into the MIGRATING window, so every schedule exercises the
    route -> move-guard -> recheck path against a mid-flight key."""
    _migration_body(writes=1, wait_for_migrating=True)


def _finalize_race_body():
    """The tightest loss window the slot-handoff protocol has: a write
    routed 'serve locally, guarded' waits on the move guard while the
    mover ships the key AND the driver finalizes ownership.  When the
    writer finally holds the guard, serving locally would land an
    acked write on a node that no longer owns the slot (lost for every
    future reader).  The REAL route_recheck must turn it away (ASK
    while still owner+migrating, MOVED once ownership changed).

    The mover here is a compressed driver: one MIGRATE then the
    SETSLOT NODE broadcast, no empty-slot re-check — legal (the slot
    has exactly one key) and exactly the window a concurrent write
    can hit even under the full pump, since a write can always land
    between the pump's last GETKEYSINSLOT and its finalize."""
    with netsim.Net() as net:
        topo = _topology([[0, NSLOTS - 1]], [])
        na = MiniClusterNode(net, ADDR_A, "A", topo)
        nb = MiniClusterNode(net, ADDR_B, "B", topo)
        na.store[KEY.decode()] = b"0"
        na.slotmap.set_migrating(SLOT, "B")
        nb.slotmap.set_importing(SLOT, "A")
        import socket as socket_mod

        # The mover's control connections dial FIRST (low scheduler
        # tids): the default DFS path then drives the finalize chain
        # ahead of the woken writer — the deepest loss interleaving is
        # an EARLY schedule, not a needle.
        mover_a = socket_mod.create_connection(ADDR_A, timeout=30.0)
        mover_b = socket_mod.create_connection(ADDR_B, timeout=30.0)
        # Seed from B only: the writer's data connection to A then
        # dials at WRITE time (highest scheduler tid), so the default
        # schedule already defers the woken writer past the whole
        # finalize chain — the deepest loss window is schedule #1.
        client = _client(ADDR_B)
        acked = [b"0"]

        def writer():
            _write_retrying(client, b"1")
            acked.append(b"1")

        def mover():
            r = exchange(mover_a, [[
                b"MIGRATE", ADDR_B[0].encode(), b"%d" % ADDR_B[1],
                KEY, b"0", b"30000",
            ]])
            assert r[0] == b"OK", r
            fin = [b"CLUSTER", b"SETSLOT", b"%d" % SLOT, b"NODE", b"B"]
            assert exchange(mover_b, [fin])[0] == b"OK"
            assert exchange(mover_a, [fin])[0] == b"OK"

        wt = threading.Thread(target=writer)
        mt = threading.Thread(target=mover)
        wt.start()
        mt.start()
        wt.join()
        mt.join()
        mover_a.close()
        mover_b.close()
        final = client.execute(b"GET", KEY)
        assert final == acked[-1], (
            f"acked write lost across the finalize race: read {final!r}, "
            f"last acked {acked[-1]!r} (source store={na.store!r}, "
            f"target store={nb.store!r})"
        )
        client.close()


@schedule_test(max_schedules=250, random_schedules=64, preemption_bound=2,
               max_steps=200000)
def test_model_migration_finalize_races_guarded_write():
    _finalize_race_body()


def test_model_migration_recheck_mutation_guard():
    """Reverting the move guard's re-check (route_recheck -> serve
    unconditionally) must be CAUGHT: some schedule lets a write that
    routed 'serve locally' proceed after the mover shipped the key —
    the acked write resurrects on the source and dies when the slot
    finalizes.  The failing schedule prints a replay token that
    reproduces it exactly."""
    orig = ClusterDoor.route_recheck
    ClusterDoor.route_recheck = lambda self, name, cmd: None
    try:
        with pytest.raises(ScheduleFailure) as ei:
            explore(_finalize_race_body, max_schedules=250,
                    random_schedules=64, preemption_bound=2,
                    max_steps=200000)
        token = ei.value.token
        with pytest.raises(ScheduleFailure) as ei2:
            explore(_finalize_race_body, replay=token, max_steps=200000)
        assert ei2.value.token == token
    finally:
        ClusterDoor.route_recheck = orig


# -- crash + retry: the pump dies mid-slot, the slot stays serveable ---------


def _pump_death_body():
    """The target node CRASHES mid-migration (netsim crash injection:
    its actors die at their next sync point, every connection resets).
    Invariants: the half-migrated slot stays serveable (writes keep
    acking through ASK once the target restarts), re-running the pump
    RESUMES, and no acked write is lost end to end."""
    with netsim.Net() as net:
        topo = _topology([[0, NSLOTS - 1]], [])
        na = MiniClusterNode(net, ADDR_A, "A", topo)
        nb = MiniClusterNode(net, ADDR_B, "B", topo)
        na.store[KEY.decode()] = b"0"
        client = _client(ADDR_A, ADDR_B)
        acked = [b"0"]
        pump_failed = []

        def writer():
            for i in range(1, 3):
                val = b"%d" % i
                _write_retrying(client, val)
                acked.append(val)

        def pump():
            try:
                supervisor_mod.migrate_slot(
                    SLOT, ADDR_A, ADDR_B, notify=(ADDR_A, ADDR_B),
                    batch=4,
                )
            except (OSError, RuntimeError) as e:
                pump_failed.append(e)  # driver death mid-pump: allowed

        def crasher():
            checkpoint("crash lands here")
            net.crash(ADDR_B)
            checkpoint("target down")
            net.restart(ADDR_B)

        threads = [threading.Thread(target=f)
                   for f in (writer, pump, crasher)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if pump_failed or na.slotmap.owner(SLOT) != "B":
            # Mid-pump death leaves the slot serveable; re-running the
            # pump RESUMES (every step idempotent) and finishes.
            supervisor_mod.migrate_slot(
                SLOT, ADDR_A, ADDR_B, notify=(ADDR_A, ADDR_B), batch=4
            )
        assert na.slotmap.owner(SLOT) == "B"
        assert nb.slotmap.owner(SLOT) == "B"
        final = client.execute(b"GET", KEY)
        assert final == acked[-1], (
            f"acked write lost across crash+retry: read {final!r}, "
            f"last acked {acked[-1]!r} (pump_failed={bool(pump_failed)})"
        )
        client.close()


@schedule_test(max_schedules=40, random_schedules=32, preemption_bound=1,
               max_steps=300000)
def test_model_migration_pump_crash_retry():
    _pump_death_body()


# ---------------------------------------------------------------------------
# model 2: the redirect protocol (MOVED exactly-once, ASK, ASKING one-shot)
# ---------------------------------------------------------------------------


def _moved_once_body():
    """A stale client table: the owner finalized A -> B after bootstrap.
    The REAL client must refresh the table ONCE and retry ONCE — both
    nodes see exactly one arrival of the command."""
    with netsim.Net() as net:
        topo = _topology([[0, NSLOTS - 1]], [])
        na = MiniClusterNode(net, ADDR_A, "A", topo)
        nb = MiniClusterNode(net, ADDR_B, "B", topo)
        client = _client(ADDR_A)
        # Ownership finalizes AFTER the client bootstrapped its table.
        na.slotmap.set_owner(SLOT, "B")
        nb.slotmap.set_owner(SLOT, "B")
        nb.store[KEY.decode()] = b"v"
        refreshes0 = client.stats["table_refreshes"]
        assert client.execute(b"GET", KEY) == b"v"
        assert client.stats["moved"] == 1
        assert client.stats["table_refreshes"] == refreshes0 + 1
        assert na.counts.get("GET", 0) == 1, "retry must go to B, not A"
        assert nb.counts.get("GET", 0) == 1, "exactly one retry"
        client.close()


@schedule_test(max_schedules=20, random_schedules=8, preemption_bound=1)
def test_model_moved_refreshes_and_retries_exactly_once():
    _moved_once_body()


def _moved_bounce_body():
    """Two nodes misconfigured to MOVED-bounce at each other: the
    bounded chase gives up after ONE retry (total two arrivals) and
    surfaces the redirect as an error instead of looping."""
    with netsim.Net() as net:
        # A's map says B owns the slot; B's map says A does.
        na = MiniClusterNode(
            net, ADDR_A, "A", _topology([[0, NSLOTS - 1]], [])
        )
        nb = MiniClusterNode(
            net, ADDR_B, "B", _topology([[0, NSLOTS - 1]], [])
        )
        na.slotmap.set_owner(SLOT, "B")
        client = _client(ADDR_A)
        with pytest.raises(ReplyError) as ei:
            client.execute(b"GET", KEY)
        assert ei.value.code == "MOVED"
        total = na.counts.get("GET", 0) + nb.counts.get("GET", 0)
        assert total == 2, (
            f"bounded chase must stop after one retry, saw {total} "
            f"arrivals"
        )
        client.close()


@schedule_test(max_schedules=20, random_schedules=8, preemption_bound=1)
def test_model_moved_bounce_gives_up_after_one_retry():
    _moved_bounce_body()


def test_model_moved_budget_mutation_guard():
    """Reverting the one-retry MOVED budget (unbounded chase) must be
    caught: the bounce scenario loops forever and the scheduler's step
    bound fails the schedule with a replayable token."""
    orig = ClusterClient._chase

    def unbounded(self, cmd, reply, moved_budget, refresh=True):
        return orig(self, cmd, reply, 1 << 30, refresh)

    ClusterClient._chase = unbounded
    try:
        with pytest.raises(ScheduleFailure) as ei:
            explore(_moved_bounce_body, max_schedules=4,
                    random_schedules=0, preemption_bound=0,
                    max_steps=4000)
        token = ei.value.token
        with pytest.raises(ScheduleFailure) as ei2:
            explore(_moved_bounce_body, replay=token,
                    preemption_bound=0, max_steps=4000)
        assert ei2.value.token == token
    finally:
        ClusterClient._chase = orig


def _ask_body():
    """ASK mid-migration: the key already shipped to B.  The client
    follows with ASKING + command and must NOT update its table."""
    with netsim.Net() as net:
        topo = _topology([[0, NSLOTS - 1]], [])
        na = MiniClusterNode(net, ADDR_A, "A", topo)
        nb = MiniClusterNode(net, ADDR_B, "B", topo)
        na.slotmap.set_migrating(SLOT, "B")
        nb.slotmap.set_importing(SLOT, "A")
        nb.store[KEY.decode()] = b"shipped"
        client = _client(ADDR_A)
        assert client.execute(b"GET", KEY) == b"shipped"
        assert client.stats["ask"] == 1
        assert client.stats["moved"] == 0
        assert client.slot_addr(SLOT) == ADDR_A, \
            "ASK must not touch the slot table"
        assert nb.counts.get("ASKING", 0) == 1
        client.close()


@schedule_test(max_schedules=20, random_schedules=8, preemption_bound=1)
def test_model_ask_handshake_no_table_update():
    _ask_body()


def _asking_one_shot_body():
    """The ASKING license is one-shot against ANY next command: a
    keyless PING between ASKING and the keyed command burns it, so the
    keyed command gets MOVED, not served (the PR 12 review leak,
    driven through the REAL consume_one_shot_licenses)."""
    with netsim.Net() as net:
        topo = _topology([[0, NSLOTS - 1]], [])
        MiniClusterNode(net, ADDR_A, "A", topo)
        nb = MiniClusterNode(net, ADDR_B, "B", topo)
        nb.slotmap.set_importing(SLOT, "A")
        nb.store[KEY.decode()] = b"early"
        import socket as socket_mod

        # License honored when fresh: ASKING + GET serves.
        s1 = socket_mod.create_connection(ADDR_B)
        r1 = exchange(s1, [[b"ASKING"], [b"GET", KEY]])
        assert r1[0] == b"OK" and r1[1] == b"early"
        s1.close()
        # A PING in between must BURN it: the keyed command redirects.
        s2 = socket_mod.create_connection(ADDR_B)
        r2 = exchange(s2, [[b"ASKING"], [b"PING"], [b"GET", KEY]])
        assert r2[0] == b"OK" and r2[1] == b"PONG"
        assert isinstance(r2[2], ReplyError) and r2[2].code == "MOVED", (
            f"ASKING license leaked past PING: keyed command replied "
            f"{r2[2]!r} instead of MOVED"
        )
        s2.close()


@schedule_test(max_schedules=20, random_schedules=8, preemption_bound=1)
def test_model_asking_license_is_one_shot():
    _asking_one_shot_body()


def test_model_asking_burn_mutation_guard():
    """Reverting the keyless-command license burn (the shipped
    consume_one_shot_licenses) must be caught: the PING no longer
    consumes ASKING and the later keyed command is served under the
    stale license."""
    orig = resp_mod.consume_one_shot_licenses
    resp_mod.consume_one_shot_licenses = lambda ctx, name: None
    try:
        with pytest.raises(ScheduleFailure) as ei:
            explore(_asking_one_shot_body, max_schedules=20,
                    random_schedules=8, preemption_bound=1)
        token = ei.value.token
        with pytest.raises(ScheduleFailure) as ei2:
            explore(_asking_one_shot_body, replay=token)
        assert ei2.value.token == token
    finally:
        resp_mod.consume_one_shot_licenses = orig


# ---------------------------------------------------------------------------
# scatter/gather demux across reordered legs
# ---------------------------------------------------------------------------


def _scatter_key_for(lo: int, hi: int) -> bytes:
    for i in range(100000):
        k = b"sk%d" % i
        if lo <= key_slot(k) <= hi:
            return k
    raise AssertionError("no key found in range")


_HALF = NSLOTS // 2
KEY_A = _scatter_key_for(0, _HALF - 1)
KEY_B = _scatter_key_for(_HALF, NSLOTS - 1)


def _scatter_body():
    """execute_many across two nodes with a deferrable link: whatever
    order the legs' replies land in, the demux returns results in
    SUBMISSION order, and a mid-batch MOVED is chased with ONE table
    refresh for the whole batch."""
    with netsim.Net(defer_budget=1, defer_s=0.5) as net:
        topo = _topology([[0, _HALF - 1]], [[_HALF, NSLOTS - 1]])
        na = MiniClusterNode(net, ADDR_A, "A", topo)
        nb = MiniClusterNode(net, ADDR_B, "B", topo)
        client = _client(ADDR_A, ADDR_B)
        r = client.execute_many([
            [b"SET", KEY_A, b"va"], [b"SET", KEY_B, b"vb"],
            [b"GET", KEY_A], [b"GET", KEY_B], [b"PING"],
        ])
        assert r == [b"OK", b"OK", b"va", b"vb", b"PONG"], r
        assert client.stats["scatter_legs"] >= 2
        # A finalize the client has not seen: the batch's KEY_A replies
        # come back MOVED, the chase refreshes ONCE and lands them.
        na.slotmap.set_owner(key_slot(KEY_A), "B")
        nb.slotmap.set_owner(key_slot(KEY_A), "B")
        nb.store[KEY_A.decode()] = b"moved-va"
        refreshes0 = client.stats["table_refreshes"]
        r2 = client.execute_many([[b"GET", KEY_A], [b"GET", KEY_B]])
        assert r2 == [b"moved-va", b"vb"], r2
        assert client.stats["table_refreshes"] == refreshes0 + 1, \
            "one refresh per batch, not per MOVED reply"
        client.close()


@schedule_test(max_schedules=60, random_schedules=32, preemption_bound=2,
               max_steps=300000)
def test_model_scatter_gather_demux_under_reordering():
    _scatter_body()


# ---------------------------------------------------------------------------
# pooled-socket desync discipline (timeout -> drop, never reuse)
# ---------------------------------------------------------------------------


def _desync_body():
    """A reply outliving its request's timeout: the pooled connection
    must be DROPPED (the PR 12 review fix) — a later command on a kept
    socket would read the late reply as its OWN (silent cross-command
    corruption).  The slow node delays its first GET reply past the
    client timeout; the retry must see the RIGHT key's value."""
    with netsim.Net() as net:
        topo = _topology([[0, NSLOTS - 1]], [])
        na = MiniClusterNode(net, ADDR_A, "A", topo,
                             slow_first_get_s=5.0)
        MiniClusterNode(net, ADDR_B, "B", topo)
        na.store["d1"] = b"v1"
        na.store["d2"] = b"v2"
        client = _client(ADDR_A, timeout_s=1.0)
        with pytest.raises(OSError):
            client.execute(b"GET", b"d1")  # reply lands at t+5, too late
        time.sleep(6.0)  # virtual: the stale reply is in flight/buffered
        got = client.execute(b"GET", b"d2")
        assert got == b"v2", (
            f"cross-command corruption: GET d2 answered {got!r} (the "
            f"timed-out GET d1's late reply) — desynced socket reused"
        )
        client.close()


@schedule_test(max_schedules=20, random_schedules=8, preemption_bound=1)
def test_model_pooled_socket_dropped_after_timeout():
    _desync_body()


def test_model_socket_drop_mutation_guard():
    """Reverting the drop-on-OSError discipline (reuse the pooled
    socket after a timeout) must be caught as cross-command reply
    corruption, with a replayable token."""
    orig = ClusterClient._request

    def keep_on_error(self, addr, cmds):
        return self._conn(addr).request(cmds)  # no drop, ever

    ClusterClient._request = keep_on_error
    try:
        with pytest.raises(ScheduleFailure) as ei:
            explore(_desync_body, max_schedules=20, random_schedules=8,
                    preemption_bound=1)
        token = ei.value.token
        with pytest.raises(ScheduleFailure) as ei2:
            explore(_desync_body, replay=token)
        assert ei2.value.token == token
    finally:
        ClusterClient._request = orig


# ---------------------------------------------------------------------------
# crash contract: outbound connections reset too
# ---------------------------------------------------------------------------


@schedule_test(max_schedules=40, random_schedules=16, preemption_bound=2)
def test_crash_resets_outbound_connections():
    """net.crash(A) resets connections A's handler actors DIALED (the
    door-pump shape: a persistent migration socket to another node),
    not just inbound ones — the peer's parked recv fails promptly
    instead of hanging the schedule on a pipe nobody will ever feed."""
    import socket as sk

    done = threading.Event()
    seen = {}

    def b_handler(sock, peer):
        try:
            seen["result"] = "data" if sock.recv(16) else "eof"
        except OSError as e:
            # ConnectionResetError when parked in recv at crash time,
            # bare OSError when the abort landed before the first recv
            # — either way the failure is prompt, which is the contract.
            seen["result"] = "reset" if isinstance(
                e, ConnectionResetError) else "closed"
        finally:
            done.set()

    def a_handler(sock, peer):
        conn = sk.create_connection(ADDR_B)  # outbound from node A
        sock.sendall(b"+dialed\r\n")
        conn.recv(16)  # parked holding the outbound socket

    with netsim.Net() as net:
        net.listen(ADDR_A, a_handler, name="A")
        net.listen(ADDR_B, b_handler, name="B")
        c = sk.create_connection(ADDR_A)
        assert c.recv(16) == b"+dialed\r\n"
        net.crash(ADDR_A)
        assert done.wait(5.0), "B never observed A's crash"
        assert seen["result"] in ("reset", "closed"), seen
