"""netsim failover-election models (ISSUE 18 tentpole): the REAL
shipped election code — :class:`FailoverState` (grant_vote's
one-vote-per-epoch record, the majority-over-ALL-primaries quorum),
:class:`FailoverAgent`'s ``_try_failover``/``_takeover`` (the vote
collection and the explicit-claim takeover broadcast, over the
patched ``socket.create_connection``), and
:meth:`SlotMap.apply_takeover`'s per-slot epoch gate — driven over a
simulated network under the schedule explorer, so the
partition × primary-crash × stale-replica-election interleavings are
ENUMERATED, not sampled.

Invariants, in EVERY schedule:

- **no-dual-primary** — no epoch has two winners, and after the dust
  settles every live node's slot map names the SAME owner for the dead
  primary's slots: the highest-epoch winner (or the dead primary
  itself when no election succeeded — safety, not liveness).
- **no-acked-write-loss** — the final owner's replication offset is at
  least the fully-acked fence (the offset every replica had acked via
  the WAIT discipline before the primary died): only replicas of the
  dead primary may succeed it, so the acked prefix is always held.

Each invariant has a reverted-fix mutation guard that puts back the
bug and asserts the model CATCHES it with a replayable token:

- reverting grant_vote's record-the-vote-BEFORE-granting line lets two
  candidates win ONE epoch (dual primary);
- reverting apply_takeover's ``_slot_epoch[s] < epoch`` gate makes the
  final owner depend on broadcast delivery order (divergent maps);
- reverting grant_vote's only-its-own-replicas check lets a replica of
  a DIFFERENT primary win the slots with none of the acked writes.
"""

import threading
import time
import types

import pytest

from redisson_tpu.analysis import netsim
from redisson_tpu.analysis.explorer import (
    ScheduleFailure,
    explore,
    schedule_test,
)
from redisson_tpu.cluster.failover import FailoverAgent, FailoverState
from redisson_tpu.cluster.slotmap import SlotMap
from redisson_tpu.cluster.slots import NSLOTS
from redisson_tpu.serve.wireutil import (
    ReplyError,
    decode_command,
    encode_reply,
)

# slow: bounded-exhaustive exploration is the protocol-check CI
# job's work (`-m netsim` selects regardless of slow); keeping the
# models out of tier-1 preserves its runtime budget.
pytestmark = [pytest.mark.netsim, pytest.mark.slow]


@pytest.fixture(autouse=True)
def _unpatch_network():
    """A failing schedule abandons the explored body mid-``with Net()``
    (its __exit__ never runs), which would leave every LATER test in
    this process dialing the sim and getting ConnectionRefusedError."""
    yield
    netsim.restore_patches()


ADDRS = {
    "A": ("prim-a", 7001),
    "B": ("prim-b", 7002),
    "C": ("prim-c", 7003),
    "R1": ("repl-1", 7004),
    "R2": ("repl-2", 7005),
    "D": ("repl-d", 7006),
}

# Replication offsets at the moment A dies.  FENCE is the fully-acked
# prefix: the highest offset EVERY replica of A had acked (the WAIT
# <all-replicas> discipline) — the loss bound failover must honor.
# R1 additionally holds a tail only IT acked; D replicates B, so it
# holds NONE of A's writes.
OFFSETS = {"R1": 100, "R2": 50, "D": 0}
FENCE = 50


def _topology(with_rogue=False):
    nodes = [
        {"id": "A", "host": ADDRS["A"][0], "port": ADDRS["A"][1],
         "slots": [[0, NSLOTS - 1]]},
        {"id": "B", "host": ADDRS["B"][0], "port": ADDRS["B"][1],
         "slots": []},
        {"id": "C", "host": ADDRS["C"][0], "port": ADDRS["C"][1],
         "slots": []},
        {"id": "R1", "host": ADDRS["R1"][0], "port": ADDRS["R1"][1],
         "slots": [], "role": "replica", "replica_of": "A"},
        {"id": "R2", "host": ADDRS["R2"][0], "port": ADDRS["R2"][1],
         "slots": [], "role": "replica", "replica_of": "A"},
    ]
    if with_rogue:
        nodes.append(
            {"id": "D", "host": ADDRS["D"][0], "port": ADDRS["D"][1],
             "slots": [], "role": "replica", "replica_of": "B"}
        )
    return {"nodes": nodes}


class ModelNode:
    """One simulated node: its OWN copies of the real SlotMap and
    FailoverState, serving the election wire surface the REAL
    FailoverAgent dials (AUTH votes, TAKEOVER broadcasts, pings)."""

    def __init__(self, net, myid, topo, applied=0):
        self.myid = myid
        self.slotmap = SlotMap.from_dict(topo)
        self.state = FailoverState(myid, self.slotmap, node_timeout=60.0)
        self.applied = applied
        net.listen(ADDRS[myid], self.serve, name=myid)

    def serve(self, sock, peer) -> None:
        buf = b""
        pos = 0
        while True:
            chunk = sock.recv(1 << 16)
            if not chunk:
                return
            buf += chunk
            while True:
                try:
                    cmd, end = decode_command(buf, pos)
                except (IndexError, ValueError):
                    break
                pos = end
                sock.sendall(self.dispatch(cmd))

    def dispatch(self, cmd) -> bytes:
        name = cmd[0].decode("latin-1", "replace").upper()
        try:
            if name == "RTPU.FAILOVER.AUTH":
                granted = self.state.grant_vote(
                    cmd[1].decode(), int(cmd[2]), cmd[3].decode()
                )
                return encode_reply(1 if granted else 0)
            if name == "RTPU.TAKEOVER":
                new, old = cmd[1].decode(), cmd[2].decode()
                epoch = int(cmd[3])
                slots = None
                if len(cmd) > 4 and cmd[4]:
                    slots = []
                    for part in cmd[4].decode().split(","):
                        a, _, b = part.partition("-")
                        slots.append([int(a), int(b or a)])
                moved = self.slotmap.apply_takeover(
                    old, new, epoch, slots=slots
                )
                self.state.note_takeover(new, old, epoch)
                return encode_reply(moved)
            if name == "RTPU.CLUSTERPING":
                e = self.state.note_ping(cmd[1].decode(), int(cmd[2]))
                return encode_reply([
                    b"PONG", self.myid.encode(), e, self.applied,
                    self.slotmap.role(self.myid).encode(),
                ])
            return encode_reply(ReplyError(f"ERR unknown '{name}'"))
        except Exception as e:  # noqa: BLE001 - the -ERR contract
            return encode_reply(ReplyError(f"ERR {e}"))


def _make_candidate(node, wins):
    """Wrap a ModelNode in the REAL FailoverAgent (not started as a
    thread — the model drives ``_try_failover`` directly, which is the
    whole election: rank, vote collection over the sim net, promote,
    claim, broadcast).  ``promote_to_primary`` records the win with
    its epoch — the dual-primary invariant's evidence."""
    server = types.SimpleNamespace(
        cluster=types.SimpleNamespace(myid=node.myid, slotmap=node.slotmap),
        obs=None,
        replica_link=types.SimpleNamespace(applied=node.applied),
        promote_to_primary=lambda epoch, m=node.myid: wins.append((m, epoch)),
    )
    agent = FailoverAgent(
        server, node_timeout_s=60.0, ping_interval_s=0.05,
        election_rank_delay_s=0.0,
    )
    agent.state = node.state  # one state per node, shared with its wire
    return agent


def _campaign(agent, rounds=3):
    """The standing-retry election loop (the agent _tick gate in
    miniature): campaign while the dead primary still owns slots ON
    THIS NODE'S MAP, stop as soon as this node won or the slots moved
    (a rival's broadcast landed)."""
    agent.state.mark_failed("A")
    for _ in range(rounds):
        if not agent.slotmap.ranges("A"):
            return
        agent._try_failover("A")
        if agent.takeovers:
            return
        time.sleep(0.01)  # virtual: let rival broadcasts land


def _check_invariants(nodes, wins):
    # no-dual-primary, half 1: an epoch is majority-minted with
    # one-vote-per-epoch voters — it can have at most ONE winner.
    epochs = [e for _, e in wins]
    assert len(epochs) == len(set(epochs)), (
        f"two candidates won one epoch: {wins}"
    )
    # no-dual-primary, half 2: every live map converges on ONE owner
    # for the dead primary's slots — the highest-epoch winner, or A
    # itself if no election succeeded (safety, not liveness).
    expect = max(wins, key=lambda t: t[1])[0] if wins else "A"
    for node in nodes:
        owners = {node.slotmap.owner(s) for s in (0, NSLOTS // 2,
                                                  NSLOTS - 1)}
        assert owners == {expect}, (
            f"{node.myid} routes A's slots to {owners}, expected "
            f"{expect!r} (wins={wins})"
        )
    # no-acked-write-loss: the final owner holds the fully-acked
    # prefix.  Only a replica of A can win, and every replica of A
    # acked through FENCE before A died.
    if wins:
        assert OFFSETS[expect] >= FENCE, (
            f"winner {expect} is {FENCE - OFFSETS[expect]} ops short "
            f"of the acked fence: acked writes lost"
        )


def _election_race_body():
    """Primary A crashes; its two replicas (one fresh, one stale) race
    the election against voters B and C."""
    with netsim.Net() as net:
        topo = _topology()
        wins: list = []
        nodes = [
            ModelNode(net, nid, topo, applied=OFFSETS.get(nid, 0))
            for nid in ("B", "C", "R1", "R2")
        ]
        by_id = {n.myid: n for n in nodes}
        for v in ("B", "C"):
            by_id[v].state.mark_failed("A")
        cands = [
            _make_candidate(by_id["R1"], wins),
            _make_candidate(by_id["R2"], wins),
        ]
        threads = [
            threading.Thread(target=_campaign, args=(a,)) for a in cands
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        _check_invariants(nodes, wins)


def _rogue_candidate_body():
    """A replica of a DIFFERENT primary (D replicates B — it holds
    none of A's writes) campaigns for A's slots alongside the
    legitimate stale replica.  grant_vote's only-its-own-replicas
    check must shut D out in every schedule."""
    with netsim.Net() as net:
        topo = _topology(with_rogue=True)
        wins: list = []
        nodes = [
            ModelNode(net, nid, topo, applied=OFFSETS.get(nid, 0))
            for nid in ("B", "C", "R1", "R2", "D")
        ]
        by_id = {n.myid: n for n in nodes}
        for v in ("B", "C"):
            by_id[v].state.mark_failed("A")
        cands = [
            _make_candidate(by_id["R2"], wins),
            _make_candidate(by_id["D"], wins),
        ]
        threads = [
            threading.Thread(target=_campaign, args=(a,)) for a in cands
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert "D" not in [w for w, _ in wins], (
            f"a replica of ANOTHER primary deposed A: {wins}"
        )
        _check_invariants(nodes, wins)


def _partition_body():
    """A crashes AND voter B is unreachable (the candidate's side of a
    partition holds one of three primaries).  Majority counts ALL
    primaries — dead and unreachable ones included — so the minority
    side must never assemble a quorum: no takeover, A's slots stay
    put (a partitioned observer keeps routing to A and fails, rather
    than being told a lie)."""
    with netsim.Net() as net:
        topo = _topology()
        wins: list = []
        nodes = [
            ModelNode(net, nid, topo, applied=OFFSETS.get(nid, 0))
            for nid in ("C", "R1", "R2")
        ]  # B never listens: partitioned away with A dead
        by_id = {n.myid: n for n in nodes}
        by_id["C"].state.mark_failed("A")
        cands = [
            _make_candidate(by_id["R1"], wins),
            _make_candidate(by_id["R2"], wins),
        ]
        threads = [
            threading.Thread(target=_campaign, args=(a,)) for a in cands
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert wins == [], f"minority partition elected a primary: {wins}"
        _check_invariants(nodes, wins)


def _double_takeover_body():
    """The compressed delivery-order window the per-slot epoch gate
    exists for: TWO legitimate takeovers of A happened in successive
    epochs (the stale replica won epoch 1, then the fresh one — whose
    map hadn't yet seen that broadcast — won epoch 2; both quorums are
    reachable in the full race model, just far down the search tree).
    Their claim broadcasts race to the observers in explored order.
    Invariant: every observer converges on the HIGHER epoch's winner
    no matter which broadcast lands last."""
    import socket as socket_mod

    from redisson_tpu.serve.wireutil import exchange

    with netsim.Net() as net:
        topo = _topology()
        wins = [("R2", 1), ("R1", 2)]
        nodes = [
            ModelNode(net, nid, topo, applied=OFFSETS.get(nid, 0))
            for nid in ("B", "C")
        ]
        spec = f"0-{NSLOTS - 1}"

        def broadcast(winner, epoch):
            # The _takeover broadcast loop in miniature: sequential
            # sends, one short-lived connection per observer.
            for nid in ("B", "C"):
                sock = socket_mod.create_connection(ADDRS[nid],
                                                    timeout=30.0)
                try:
                    exchange(sock, [(
                        "RTPU.TAKEOVER", winner, "A", str(epoch), spec,
                    )])
                finally:
                    sock.close()

        threads = [
            threading.Thread(target=broadcast, args=w) for w in wins
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        _check_invariants(nodes, wins)


# ---------------------------------------------------------------------------
# the models
# ---------------------------------------------------------------------------


@schedule_test(max_schedules=150, random_schedules=48, preemption_bound=2,
               max_steps=200000)
def test_model_election_race_single_winner():
    _election_race_body()


@schedule_test(max_schedules=100, random_schedules=32, preemption_bound=2,
               max_steps=200000)
def test_model_rogue_candidate_never_wins():
    _rogue_candidate_body()


@schedule_test(max_schedules=60, random_schedules=24, preemption_bound=2,
               max_steps=200000)
def test_model_minority_partition_never_elects():
    _partition_body()


@schedule_test(max_schedules=100, random_schedules=32, preemption_bound=2,
               max_steps=200000)
def test_model_double_takeover_delivery_order_converges():
    _double_takeover_body()


# ---------------------------------------------------------------------------
# mutation guards: revert each fix, watch the model catch it, replay it
# ---------------------------------------------------------------------------


def _explore_expect_caught(body, **opts):
    """Run the explorer expecting a ScheduleFailure; re-run its replay
    token and check it reproduces the SAME failing schedule."""
    with pytest.raises(ScheduleFailure) as ei:
        explore(body, **opts)
    token = ei.value.token
    with pytest.raises(ScheduleFailure) as ei2:
        explore(body, replay=token, max_steps=opts.get("max_steps", 200000))
    assert ei2.value.token == token


def test_model_mutation_unrecorded_vote_dual_primary():
    """Revert grant_vote's record-the-vote-BEFORE-granting line: a
    voter hands BOTH candidates its vote in one epoch, both assemble a
    majority, and two primaries serve one slot range.  The model must
    catch it with a replayable token."""
    orig = FailoverState.grant_vote

    def grant_without_recording(self, candidate_id, epoch,
                                failed_primary_id):
        epoch = int(epoch)
        with self._lock:
            if epoch <= self.last_vote_epoch:
                return False
            if failed_primary_id not in self.failed:
                return False
            if self.slotmap.replica_of(candidate_id) != failed_primary_id:
                return False
            # MUTATION: the vote is never recorded.
            self.current_epoch = max(self.current_epoch, epoch)
            return True

    FailoverState.grant_vote = grant_without_recording
    try:
        _explore_expect_caught(
            _election_race_body, max_schedules=150, random_schedules=48,
            preemption_bound=2, max_steps=200000,
        )
    finally:
        FailoverState.grant_vote = orig


def test_model_mutation_unranked_takeover_diverges():
    """Revert apply_takeover's per-slot epoch gate (apply every claim
    unconditionally): when two candidates win successive epochs, the
    final owner on each node becomes whichever broadcast arrived LAST
    — maps diverge, two primaries each serve the slots for part of
    the cluster.  The model must catch the divergence."""
    orig = SlotMap.apply_takeover

    def apply_unconditionally(self, old_id, new_id, epoch, slots=None):
        epoch = int(epoch)
        with self._lock:
            if new_id not in self._nodes:
                raise KeyError(f"unknown node id {new_id!r}")
            if slots is None:
                claim = [
                    s for s in range(NSLOTS) if self._owner[s] == old_id
                ]
            else:
                claim = []
                for start, end in slots:
                    claim.extend(range(int(start), int(end) + 1))
            moved = 0
            for s in claim:
                # MUTATION: no `_slot_epoch[s] < epoch` gate.
                self._owner[s] = new_id
                self._slot_epoch[s] = epoch
                moved += 1
            if moved:
                self._roles[new_id] = "master"
                self._replica_of.pop(new_id, None)
                if old_id in self._nodes:
                    self._roles[old_id] = "replica"
                    self._replica_of[old_id] = new_id
                self.epoch += 1
            return moved

    SlotMap.apply_takeover = apply_unconditionally
    try:
        _explore_expect_caught(
            _double_takeover_body, max_schedules=100, random_schedules=32,
            preemption_bound=2, max_steps=200000,
        )
    finally:
        SlotMap.apply_takeover = orig


def test_model_mutation_unchecked_lineage_loses_acked_writes():
    """Revert grant_vote's only-its-own-replicas check: D (a replica
    of B, holding NONE of A's acked writes) can win A's slots — every
    acked write on that range is gone.  The model must catch it."""
    orig = FailoverState.grant_vote

    def grant_any_lineage(self, candidate_id, epoch, failed_primary_id):
        epoch = int(epoch)
        with self._lock:
            if epoch <= self.last_vote_epoch:
                return False
            if failed_primary_id not in self.failed:
                return False
            # MUTATION: no replica-of-the-failed-primary check.
            self.last_vote_epoch = epoch
            self.current_epoch = max(self.current_epoch, epoch)
            return True

    FailoverState.grant_vote = grant_any_lineage
    try:
        _explore_expect_caught(
            _rogue_candidate_body, max_schedules=100, random_schedules=32,
            preemption_bound=2, max_steps=200000,
        )
    finally:
        FailoverState.grant_vote = orig
