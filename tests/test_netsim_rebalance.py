"""netsim rebalancer models (ISSUE 19): the REAL assigner executor —
``rebalancer.run_wave`` with its last-moment ``blocked_reason`` gate —
racing organic ``supervisor.migrate_slot`` pumps, stale plans left by
failover takeovers, and failover-excluded nodes, over the simulated
network with the schedule explorer enumerating interleavings.

Invariant in EVERY schedule: the fleet's slot maps converge on exactly
one owner per slot, and every acked value is readable at that owner —
the assigner can only ever skip (busy / stale / failover), never strand
a slot unowned or doubly-owned.

The two mutation guards revert the assigner's protections one at a
time — :func:`rebalancer.slot_in_migration` (a second driver races a
mid-pump slot toward a DIFFERENT destination: divergent owners) and
:func:`rebalancer.owner_matches` (a stale plan finalizes ownership away
from the node actually holding the keys: acked data stranded) — and
assert the models CATCH the regression with a replayable
``RTPU_SCHEDULE_REPLAY`` token.
"""

import threading

import pytest

from redisson_tpu.analysis import netsim
from redisson_tpu.analysis.explorer import (
    ScheduleFailure,
    explore,
    schedule_test,
)
from redisson_tpu.cluster import rebalancer as rb_mod
from redisson_tpu.cluster import supervisor as supervisor_mod
from redisson_tpu.cluster.client import ClusterClient
from redisson_tpu.cluster.rebalancer import Move, RebalancePlanner, run_wave
from redisson_tpu.cluster.slots import NSLOTS, key_slot
from test_netsim import MiniClusterNode

pytestmark = pytest.mark.netsim


@pytest.fixture(autouse=True)
def _unpatch_network():
    """A failing schedule abandons the body mid-``with Net()``; restore
    the real socket layer so later tests don't dial the dead sim."""
    yield
    netsim.restore_patches()


ADDR_A = ("rb-node-a", 7101)
ADDR_B = ("rb-node-b", 7102)
ADDR_C = ("rb-node-c", 7103)

TOPO3 = {"nodes": [
    {"id": "A", "host": ADDR_A[0], "port": ADDR_A[1],
     "slots": [[0, NSLOTS - 1]]},
    {"id": "B", "host": ADDR_B[0], "port": ADDR_B[1], "slots": []},
    {"id": "C", "host": ADDR_C[0], "port": ADDR_C[1], "slots": []},
]}

KEY = b"k"
SLOT = key_slot(KEY)


def _hot_key_pair():
    """Two keys in two DIFFERENT slots (the planner-driven wave model
    needs a divisible load: one mega slot legally never moves)."""
    first = b"h0"
    for i in range(1, 100000):
        k = b"h%d" % i
        if key_slot(k) != key_slot(first):
            return first, k
    raise AssertionError("no second slot found")


HOT1, HOT2 = _hot_key_pair()


def _spawn3(net):
    na = MiniClusterNode(net, ADDR_A, "A", TOPO3)
    nb = MiniClusterNode(net, ADDR_B, "B", TOPO3)
    nc = MiniClusterNode(net, ADDR_C, "C", TOPO3)
    return na, nb, nc


def _client(*seeds):
    c = ClusterClient(list(seeds), timeout_s=30.0, deadnode_attempts=0)
    c._pool = netsim.SimThreadExecutor()
    return c


def _assert_converged(nodes, slot, owner, key, value):
    """The never-strand invariant: every map agrees on ``owner``, no
    residual migration state, and the acked ``value`` lives exactly at
    the owner."""
    by_id = {n.door.myid: n for n in nodes}
    owners = {n.door.myid: n.slotmap.owner(slot) for n in nodes}
    assert set(owners.values()) == {owner}, (
        f"divergent ownership for slot {slot}: {owners}"
    )
    for n in nodes:
        d = n.slotmap.lookup(slot)
        assert d.importing_from is None and d.migrating_to is None, (
            f"{n.door.myid} kept migration state on finalized slot "
            f"{slot}"
        )
    holder = by_id[owner]
    assert holder.store.get(key.decode()) == value, (
        f"acked value stranded: owner {owner} holds "
        f"{holder.store.get(key.decode())!r}, expected {value!r}"
    )
    for n in nodes:
        if n is not holder:
            assert key.decode() not in n.store, (
                f"key duplicated on non-owner {n.door.myid}"
            )


# ---------------------------------------------------------------------------
# model 1: the assigner races a mid-pump organic migration
# ---------------------------------------------------------------------------


def _busy_race_body():
    """An organic ``migrate_slot`` A->B is mid-pump (IMPORTING/MIGRATING
    already up) when the assigner executes a wave moving the SAME slot
    toward C.  The last-moment ``blocked_reason`` must turn the wave
    away (busy while pumping, stale once finalized) in EVERY
    interleaving — a second driver finalizing the slot toward a
    different destination than the one receiving keys is exactly how a
    slot ends up doubly-owned."""
    with netsim.Net() as net:
        na, nb, nc = _spawn3(net)
        na.store[KEY.decode()] = b"0"
        na.slotmap.set_migrating(SLOT, "B")
        nb.slotmap.set_importing(SLOT, "A")
        records = []

        def organic():
            # Resumable by design: a racing driver re-runs the pump.
            for _ in range(4):
                try:
                    supervisor_mod.migrate_slot(
                        SLOT, ADDR_A, ADDR_B,
                        notify=(ADDR_A, ADDR_B, ADDR_C), batch=4,
                    )
                except (OSError, RuntimeError):
                    continue
                return
            raise AssertionError("organic pump never completed")

        def assigner():
            records.extend(run_wave(
                na.slotmap, [Move(SLOT, "A", "C", 1.0)]
            ))

        ot = threading.Thread(target=organic)
        at = threading.Thread(target=assigner)
        ot.start()
        at.start()
        ot.join()
        at.join()
        assert records and records[0]["outcome"] in (
            "skip_busy", "skip_stale"
        ), records
        _assert_converged((na, nb, nc), SLOT, "B", KEY, b"0")


@schedule_test(max_schedules=40, random_schedules=24, preemption_bound=2,
               max_steps=200000)
def test_model_assigner_skips_mid_pump_slot():
    _busy_race_body()


def test_model_busy_check_mutation_guard():
    """Reverting the in-migration check (netsim guard #1): the wave no
    longer sees the organic pump and drives a second migration of the
    same slot toward C — some schedule diverges the fleet's owner maps
    or strands the key, and the failure replays from its token."""
    orig = rb_mod.slot_in_migration
    rb_mod.slot_in_migration = lambda slotmap, slot: False
    try:
        with pytest.raises(ScheduleFailure) as ei:
            explore(_busy_race_body, max_schedules=40,
                    random_schedules=24, preemption_bound=2,
                    max_steps=200000)
        token = ei.value.token
        with pytest.raises(ScheduleFailure) as ei2:
            explore(_busy_race_body, replay=token, max_steps=200000)
        assert ei2.value.token == token
    finally:
        rb_mod.slot_in_migration = orig


# ---------------------------------------------------------------------------
# model 2: a stale plan after the slot already moved (takeover/reshard)
# ---------------------------------------------------------------------------


def _stale_plan_body():
    """Between planning and execution the slot finalized A->B (organic
    reshard or a failover takeover) and the acked value lives on B.
    The stale move still says "pump A->C"; ``owner_matches`` against
    the coordinator's CURRENT map must skip it — executing would
    finalize ownership to C while B holds the only copy of the data
    (acked write lost for every future reader)."""
    with netsim.Net() as net:
        na, nb, nc = _spawn3(net)
        for n in (na, nb, nc):
            n.slotmap.set_owner(SLOT, "B")
        nb.store[KEY.decode()] = b"1"
        client = _client(ADDR_B)
        stale = Move(SLOT, "A", "C", 1.0)
        records = []

        def assigner():
            records.extend(run_wave(nc.slotmap, [stale]))

        def reader():
            assert client.execute(b"GET", KEY) == b"1", (
                "acked value unreadable after the stale wave"
            )

        at = threading.Thread(target=assigner)
        rt = threading.Thread(target=reader)
        at.start()
        rt.start()
        at.join()
        rt.join()
        assert records and records[0]["outcome"] == "skip_stale", records
        _assert_converged((na, nb, nc), SLOT, "B", KEY, b"1")
        assert client.execute(b"GET", KEY) == b"1"
        client.close()


@schedule_test(max_schedules=30, random_schedules=16, preemption_bound=2,
               max_steps=200000)
def test_model_assigner_skips_stale_plan():
    _stale_plan_body()


def test_model_owner_check_mutation_guard():
    """Reverting the owner re-check (netsim guard #2): the stale plan
    pumps from a node that no longer owns the slot — the empty pump
    happily finalizes NODE C fleet-wide while the acked value sits on
    B, and the reader loses it.  Caught with a replayable token."""
    orig = rb_mod.owner_matches
    rb_mod.owner_matches = lambda slotmap, move: True
    try:
        with pytest.raises(ScheduleFailure) as ei:
            explore(_stale_plan_body, max_schedules=30,
                    random_schedules=16, preemption_bound=2,
                    max_steps=200000)
        token = ei.value.token
        with pytest.raises(ScheduleFailure) as ei2:
            explore(_stale_plan_body, replay=token, max_steps=200000)
        assert ei2.value.token == token
    finally:
        rb_mod.owner_matches = orig


# ---------------------------------------------------------------------------
# model 3: failover-excluded nodes are untouchable (and undialed)
# ---------------------------------------------------------------------------


CS = (SLOT + 1) % NSLOTS  # a slot C owns in the exclusion model

TOPO_C_OWNS = {"nodes": [
    {"id": "A", "host": ADDR_A[0], "port": ADDR_A[1],
     "slots": [r for r in ([0, CS - 1], [CS + 1, NSLOTS - 1])
               if r[0] <= r[1]]},
    {"id": "B", "host": ADDR_B[0], "port": ADDR_B[1], "slots": []},
    {"id": "C", "host": ADDR_C[0], "port": ADDR_C[1],
     "slots": [[CS, CS]]},
]}


def _failover_exclusion_body():
    """C is marked failed by the failover plane: a wave scheduled
    before the verdict must skip every move touching C — as source
    (its keys are unreachable) and as destination (landing slots on a
    dead node IS stranding them) — without opening one socket to it."""
    with netsim.Net() as net:
        na = MiniClusterNode(net, ADDR_A, "A", TOPO_C_OWNS)
        nb = MiniClusterNode(net, ADDR_B, "B", TOPO_C_OWNS)
        nc = MiniClusterNode(net, ADDR_C, "C", TOPO_C_OWNS)
        na.store[KEY.decode()] = b"0"
        recs = run_wave(na.slotmap, [
            Move(SLOT, "A", "C", 2.0),
            Move(CS, "C", "B", 1.0),
        ], excluded=("C",))
        assert [r["outcome"] for r in recs] == [
            "skip_failover", "skip_failover"
        ], recs
        assert nc.counts == {}, (
            f"wave dialed the failed node: {nc.counts}"
        )
        _assert_converged((na, nb, nc), SLOT, "A", KEY, b"0")


@schedule_test(max_schedules=10, random_schedules=4, preemption_bound=1)
def test_model_assigner_never_touches_failed_node():
    _failover_exclusion_body()


# ---------------------------------------------------------------------------
# model 4: a planner-driven wave under concurrent acked writes
# ---------------------------------------------------------------------------


def _planned_wave_body():
    """The full assigner loop over the sim: the PURE planner ingests a
    skewed load (two hot slots on A, B idle), plans a shed wave, and
    ``run_wave`` executes it through the real migration pump while a
    writer keeps landing acked writes on a moving slot.  In every
    schedule: the planned slot finalizes on B fleet-wide and the last
    ACKED value is what a read returns — the assigner inherits
    migrate_slot's zero-acked-write-loss discipline wholesale."""
    with netsim.Net() as net:
        na, nb, nc = _spawn3(net)
        s1, s2 = key_slot(HOT1), key_slot(HOT2)
        na.store[HOT1.decode()] = b"0"
        na.store[HOT2.decode()] = b"0"
        planner = RebalancePlanner(warmup_ticks=1, threshold=1.2)
        planner.observe({"A": {s1: (0.0, 0.0, 1), s2: (0.0, 0.0, 1)}},
                        now=0.0)
        planner.observe(
            {"A": {s1: (100.0, 0.0, 1), s2: (100.0, 0.0, 1)}}, now=1.0
        )
        owners = {s1: "A", s2: "A"}
        moves = planner.plan(owners, ["A", "B"], excluded=("C",), now=1.0)
        # Equal heat, ratio 2.0: exactly one slot sheds (the second
        # would overshoot past the mega-slot half-gap rule).
        assert len(moves) == 1 and moves[0].dst == "B"
        hot_key = HOT1 if moves[0].slot == s1 else HOT2
        client = _client(ADDR_A, ADDR_B)
        acked = [b"0"]

        def wave():
            recs = []
            for _ in range(4):
                recs = run_wave(na.slotmap, moves, excluded=("C",),
                                batch=4)
                if recs and recs[0]["outcome"] == "moved":
                    return
            raise AssertionError(f"wave never completed: {recs}")

        # The writer targets the key on the MOVING slot so schedules
        # land writes before, during, and after the pump.
        wt = threading.Thread(
            target=lambda: _writes(client, hot_key, acked)
        )
        pt = threading.Thread(target=wave)
        wt.start()
        pt.start()
        wt.join()
        pt.join()
        _assert_converged(
            (na, nb, nc), moves[0].slot, "B", hot_key, acked[-1]
        )
        final = client.execute(b"GET", hot_key)
        assert final == acked[-1], (
            f"acked write lost across the planned wave: read {final!r},"
            f" last acked {acked[-1]!r}"
        )
        client.close()


def _writes(client, key, acked, n=2):
    """Acked writes retried through fault windows (idempotent SET: the
    ACK is the contract, un-acked attempts are unconstrained)."""
    import time

    from redisson_tpu.cluster.client import ClusterError
    from redisson_tpu.serve.wireutil import ReplyError

    for i in range(1, n + 1):
        val = b"%d" % i
        for _ in range(60):
            try:
                r = client.execute(b"SET", key, val)
            except (OSError, ClusterError):
                time.sleep(0.05)  # virtual
                continue
            except ReplyError as e:
                if e.code in ("TRYAGAIN", "CLUSTERDOWN"):
                    time.sleep(0.05)
                    continue
                raise
            assert r == b"OK"
            acked.append(val)
            break
        else:
            raise AssertionError("write never acked")


@schedule_test(max_schedules=50, random_schedules=24, preemption_bound=2,
               max_steps=300000)
def test_model_planned_wave_no_acked_write_lost():
    _planned_wave_body()
