"""netsim recovery models (ISSUE 15 tentpole, models 3+4): the REAL
group-commit journal crash-recovered at schedule-chosen points, and the
REAL residency transition protocol racing snapshots.

Model 3 — group-commit + recovery: producers append to a real
``OpJournal`` (``appendfsync always``) while a crash actor kills the
writer thread at a schedule-chosen point, optionally arming the
torn-tail fault first, and with a schedule-chosen SEVERITY (process
kill -9: flushed bytes survive; host crash: ``HostCrashDisk`` rolls
every file back to its last fsynced size).  Recovery (a fresh journal
scan over the same directory) must yield a contiguous prefix that
covers EVERY acked record, wherever the crash landed in the
append → write → fsync → ack pipeline.  The mutation guard reverts the
commit barrier (ack at write time instead of fsync time) and the model
catches it with a replayable token.

Model 4 — residency × snapshot: the REAL ``ResidencyManager.demote``/
``promote`` transition code (drain → capture → install, repoint-row-
BEFORE-drop-mirror, quarantine) runs against a stub engine while a
gate-disciplined writer, a gate-free reader, and a gate-held snapshot
reader race it.  No schedule may serve a read from nowhere (no mirror
AND no row) or a state missing an acked write; the snapshot must equal
the acked set exactly.  The mutation guard re-orders promotion into
drop-mirror-then-repoint (the ordering the shipped code forbids) and
the model catches the gap with a replayable token.
"""

import os
import tempfile
import threading
import time
import types

import numpy as np
import pytest

from redisson_tpu import chaos as _chaos
from redisson_tpu.analysis import explorer, netsim
from redisson_tpu.analysis.explorer import (
    ScheduleFailure,
    checkpoint,
    explore,
    schedule_test,
)
from redisson_tpu.durability.journal import JournalError, OpJournal
from redisson_tpu.objects import degraded as degraded_mod
from redisson_tpu.ops import bitset as bitset_ops
from redisson_tpu.ops import golden  # noqa: F401  (pre-import for sim threads)
from redisson_tpu.storage import residency as rsd
from redisson_tpu.tenancy import PoolKind

pytestmark = pytest.mark.netsim


@pytest.fixture(autouse=True)
def _unpatch_netsim():
    """A failing schedule abandons the explored body mid-``with``
    (Net/HostCrashDisk __exit__ never runs), which would leave the
    sim patches live for every LATER test in this process."""
    yield
    netsim.restore_patches()


# ---------------------------------------------------------------------------
# model 3: group-commit journal vs crash, at every pipeline stage
# ---------------------------------------------------------------------------


def _journal_crash_body(journal_cls):
    tmp = tempfile.mkdtemp(prefix="rtpu-netsim-journal-")
    acked: list = []
    with netsim.HostCrashDisk() as disk:
        j = journal_cls(tmp, fsync_policy="always",
                        max_segment_bytes=1 << 20)

        def producer(base):
            for i in range(2):
                try:
                    seq = j.append({"op": "x", "i": base + i})
                except JournalError:
                    return  # broken journal refuses: not acked, fine
                try:
                    ok = j.wait_durable(seq, timeout=3.0)
                except JournalError:
                    ok = False
                if ok:
                    acked.append(seq)

        def crasher():
            checkpoint("crash lands here")
            if explorer.decide(2, "torn-tail?") == 1:
                # Crash MID-FRAME: the writer emits half a frame and
                # breaks (the chaos torn-tail point, rate 1.0 = the
                # very next frame).
                _chaos.inject("journal.torn_tail", "error", rate=1.0)
                checkpoint("armed: next frame tears")
            explorer.kill(j._writer)
            # Severity: kill -9 (OS survives, flushed bytes incl. the
            # torn half-frame remain) vs host crash (everything past
            # the last fsync is gone).
            keep = explorer.decide(2, "kill9-vs-host-crash") == 0
            disk.crash(tmp, keep_written=keep)

        threads = [
            threading.Thread(target=producer, args=(100,)),
            threading.Thread(target=producer, args=(200,)),
            threading.Thread(target=crasher),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        _chaos.clear()
    # "Restart": a fresh journal scans the directory — torn tails
    # truncate, later segments drop (durability/journal.py recovery).
    r = OpJournal(tmp, fsync_policy="always")
    recovered = [seq for seq, _rec in r.records_after(0)]
    r.close()
    assert recovered == list(range(1, len(recovered) + 1)), (
        f"recovery is not a contiguous prefix: {recovered}"
    )
    missing = [s for s in acked if s not in recovered]
    assert not missing, (
        f"acked records lost across the crash: {missing} "
        f"(acked={sorted(acked)}, recovered through "
        f"{len(recovered)})"
    )


@schedule_test(max_schedules=150, random_schedules=64, preemption_bound=2,
               max_steps=400000)
def test_model_journal_recovery_covers_acked_prefix():
    _journal_crash_body(OpJournal)


def _journal_slow_fsync_crash_body(journal_cls):
    """The ack-vs-fsync ORDER under a slow disk: chaos latency pins
    every group-commit fsync at 30 virtual seconds, a crash actor
    kills the node mid-fsync, and the host-crash severity rolls the
    files back to the last fsync.  The real journal acks only AFTER
    the fsync, so nothing acked can be lost; the reverted barrier
    (ack at write) acks into exactly this window."""
    tmp = tempfile.mkdtemp(prefix="rtpu-netsim-journal-")
    acked: list = []
    with netsim.HostCrashDisk() as disk:
        _chaos.inject("journal.fsync", "latency", rate=1.0,
                      latency_s=30.0)
        try:
            j = journal_cls(tmp, fsync_policy="always",
                            max_segment_bytes=1 << 20)

            def producer(base):
                for i in range(2):
                    try:
                        seq = j.append({"op": "x", "i": base + i})
                    except JournalError:
                        return
                    try:
                        ok = j.wait_durable(seq, timeout=3.0)
                    except JournalError:
                        ok = False
                    if ok:
                        acked.append(seq)

            def crasher():
                time.sleep(1.0)  # virtual: the writer is mid-fsync
                explorer.kill(j._writer)
                keep = explorer.decide(2, "kill9-vs-host-crash") == 0
                disk.crash(tmp, keep_written=keep)

            threads = [
                threading.Thread(target=producer, args=(100,)),
                threading.Thread(target=producer, args=(200,)),
                threading.Thread(target=crasher),
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            _chaos.clear()
    r = OpJournal(tmp, fsync_policy="always")
    recovered = [seq for seq, _rec in r.records_after(0)]
    r.close()
    assert recovered == list(range(1, len(recovered) + 1)), (
        f"recovery is not a contiguous prefix: {recovered}"
    )
    missing = [s for s in acked if s not in recovered]
    assert not missing, (
        f"acked records lost across the mid-fsync crash: {missing} "
        f"(acked={sorted(acked)}, recovered={recovered})"
    )


@schedule_test(max_schedules=60, random_schedules=32, preemption_bound=2,
               max_steps=400000)
def test_model_journal_ack_waits_out_the_slow_fsync():
    _journal_slow_fsync_crash_body(OpJournal)


class _AckAtWrite(OpJournal):
    """The reverted commit barrier: durability reported at WRITE time.
    Correct-looking under a clean run (the fsync still happens soon) —
    only a crash landing between the write-ack and the fsync shows the
    lie, which is exactly the schedule the model hunts."""

    def _write_batch(self, batch):
        super()._write_batch(batch)
        with self._lock:
            self._durable_seq = self._written_seq
            self._durable_cv.notify_all()


def test_model_journal_ack_barrier_mutation_guard():
    with pytest.raises(ScheduleFailure) as ei:
        explore(lambda: _journal_slow_fsync_crash_body(_AckAtWrite),
                max_schedules=300, random_schedules=128,
                preemption_bound=2, max_steps=400000)
    token = ei.value.token
    with pytest.raises(ScheduleFailure) as ei2:
        explore(lambda: _journal_slow_fsync_crash_body(_AckAtWrite),
                replay=token, max_steps=400000)
    assert ei2.value.token == token


# ---------------------------------------------------------------------------
# model 4: residency transitions vs concurrent reads and snapshots
# ---------------------------------------------------------------------------

_ROW_UNITS = 4  # 128 bits


class _StubPool:
    def __init__(self, rows):
        self.spec = types.SimpleNamespace(
            dtype=np.uint32, kind=PoolKind.BITSET
        )
        self.row_units = _ROW_UNITS
        self.topology_epoch = 0
        self._dispatch_lock = threading.Lock()
        self._rows = rows
        self._free = [1, 2, 3]

    def alloc_row(self) -> int:
        r = self._free.pop(0)
        self._rows[r] = np.zeros(_ROW_UNITS, np.uint32)
        return r

    def free_row(self, r) -> None:
        self._free.append(r)


class _StubExecutor:
    """Device rows as host arrays, with scheduling points where the
    real executor would cross the device boundary."""

    def __init__(self, rows):
        self._rows = rows

    def read_row(self, pool, row):
        checkpoint("device read in flight")
        return np.array(self._rows[row])

    def write_row(self, pool, row, arr):
        checkpoint("device write in flight")
        self._rows[row] = np.array(arr, dtype=np.uint32)

    def zero_row(self, pool, row):
        self._rows[row] = np.zeros(_ROW_UNITS, np.uint32)


class _StubHealth:
    @staticmethod
    def degraded_kind(kind):
        return False


def _stub_engine():
    rows = {0: np.zeros(_ROW_UNITS, np.uint32)}
    pool = _StubPool(rows)
    eng = types.SimpleNamespace(
        _journal_gate=threading.RLock(),
        _mirror_lock=threading.RLock(),
        _mirrors={},
        _mirror_epoch=0,
        health=_StubHealth(),
        executor=_StubExecutor(rows),
        _drain=lambda: checkpoint("coalescer drain"),
    )
    entry = types.SimpleNamespace(
        name="t", kind=PoolKind.BITSET, row=0, replica_rows=(),
        pool=pool, residency=rsd.DEVICE, params={},
    )
    eng._live_lookup = lambda name: entry if name == "t" else None
    return eng, entry, rows


def _bits_of(row: np.ndarray) -> set:
    out = set()
    for w, word in enumerate(np.asarray(row, np.uint32)):
        for b in range(32):
            if int(word) & (1 << b):
                out.add(w * 32 + b)
    return out


def _set_bit(row: np.ndarray, bit: int) -> None:
    row[bit // 32] |= np.uint32(1 << (bit % 32))


def _rm_for(eng, manager_cls=rsd.ResidencyManager):
    cfg = types.SimpleNamespace(
        residency_device_rows=1, residency_max_host_bytes=0,
        residency_max_disk_bytes=0, residency_promote_heat=1.0,
        residency_interval_ms=100, residency_dir=None,
        residency_heat_half_life_s=10.0,
    )
    return manager_cls(eng, cfg)


def _read_location(eng, entry, rows):
    """The engine read discipline: capture row BEFORE the mirror
    check, resolve via the mirror or the (possibly quarantined,
    contents-intact) captured row — residency.py's read contract."""
    row0 = entry.row
    checkpoint("reader captured row")
    with eng._mirror_lock:
        mir = eng._mirrors.get("t")
        if mir is not None:
            return _bits_of(mir.encode(_ROW_UNITS))
    r = entry.row if row0 < 0 else row0
    assert r >= 0, (
        "read dispatched with NO mirror and NO device row — the "
        "promote repoint-before-drop ordering was violated"
    )
    checkpoint("device read in flight")
    return _bits_of(rows[r])


def _residency_body(manager_cls=rsd.ResidencyManager, full_cast=True):
    eng, entry, rows = _stub_engine()
    rm = _rm_for(eng, manager_cls)
    acked: list = []

    def writer():
        # The engine's mutating-op discipline: the whole
        # check-residency -> submit window under the journal gate.
        for bit in (1, 66):
            with eng._journal_gate:
                with eng._mirror_lock:
                    mir = eng._mirrors.get("t")
                    if mir is not None:
                        # HOST-resident: the mirror IS the truth —
                        # the REAL kind mirror applies the op.
                        mir.mixed(
                            np.array([bit]),
                            np.array([bitset_ops.OP_SET], np.uint32),
                        )
                        applied = True
                    else:
                        applied = False
                if not applied:
                    r = entry.row
                    assert r >= 0, "write dispatched with no tier"
                    checkpoint("write queued behind the gate")
                    _set_bit(rows[r], bit)
                acked.append(bit)
            checkpoint("between writes")

    def mover():
        # The REAL transitions (drain -> capture -> install; write-row
        # -> repoint -> drop; quarantine instead of free).
        rm.demote("t")
        checkpoint("demoted")
        rm.promote("t")

    def reader():
        lo = list(acked)  # acked before this read began
        got = _read_location(eng, entry, rows)
        for b in lo:
            assert b in got, (
                f"stale read: bit {b} was acked before the read began "
                f"but is missing (got {sorted(got)})"
            )

    def snapshotter():
        # The snapshot capture discipline: gate + drain quiesce writers
        # AND transitions, so the captured state equals the acked set.
        with eng._journal_gate:
            eng._drain()
            with eng._mirror_lock:
                mir = eng._mirrors.get("t")
                if mir is not None:
                    got = _bits_of(mir.encode(_ROW_UNITS))
                else:
                    assert entry.row >= 0, \
                        "snapshot found no mirror and no row"
                    got = _bits_of(rows[entry.row])
            assert got == set(acked), (
                f"snapshot diverges from the acked set: captured "
                f"{sorted(got)}, acked {sorted(set(acked))}"
            )

    cast = (
        (writer, mover, reader, snapshotter) if full_cast
        else (mover, reader)
    )
    if not full_cast:
        # The focused mutation-hunt cast starts HOST-resident so the
        # first transition is the promotion under test.
        rm.demote("t")
    threads = [threading.Thread(target=f) for f in cast]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # Quiescent reclaim: the quarantined demotion row zeroes and frees
    # only now (the real post-drain cycle); then the final truth must
    # hold every acked write on whatever tier serves.
    rm.reclaim()
    with eng._mirror_lock:
        mir = eng._mirrors.get("t")
        truth = (
            _bits_of(mir.encode(_ROW_UNITS)) if mir is not None
            else _bits_of(rows[entry.row])
        )
    assert truth == set(acked), (
        f"acked-write loss across transitions: truth {sorted(truth)}, "
        f"acked {sorted(set(acked))}"
    )


@schedule_test(max_schedules=800, random_schedules=128,
               preemption_bound=2, max_steps=200000)
def test_model_residency_transitions_vs_snapshot():
    _residency_body()


class _PromoteDropsMirrorFirst(rsd.ResidencyManager):
    """The named mutation: promotion drops the mirror BEFORE the row
    is written and repointed (and repoints in a second lock section) —
    the ordering storage/residency.py's promote() exists to forbid."""

    def promote(self, name):
        eng = self._eng
        with eng._journal_gate:
            entry = eng._live_lookup(name)
            if entry is None or entry.row >= 0:
                return False
            with eng._mirror_lock:
                mirror = eng._mirrors.get(name)
                if mirror is None or getattr(
                    mirror, "residency", None
                ) != rsd.HOST:
                    return False
                row = entry.pool.alloc_row()
                enc = np.asarray(mirror.encode(entry.pool.row_units))
                del eng._mirrors[name]
                eng._mirror_epoch += 1
            checkpoint("BUG window: no mirror, no row")
            eng.executor.write_row(entry.pool, row, enc)
            with eng._mirror_lock:
                entry.row = row
                entry.residency = rsd.DEVICE
            with self._lock:
                self._host_nbytes.pop(name, None)
            self.promotions += 1
        return True


def test_model_residency_promote_order_mutation_guard():
    body = lambda: _residency_body(  # noqa: E731
        manager_cls=_PromoteDropsMirrorFirst, full_cast=False
    )
    with pytest.raises(ScheduleFailure) as ei:
        explore(body, max_schedules=800, random_schedules=128,
                preemption_bound=2, max_steps=200000)
    token = ei.value.token
    with pytest.raises(ScheduleFailure) as ei2:
        explore(body, replay=token, max_steps=200000)
    assert ei2.value.token == token


def test_mirror_for_entry_is_the_real_codec():
    """Sanity pin: the model's mirror IS objects/degraded.py's (the
    transition protocol under test round-trips through the real
    codec, not a test double)."""
    eng, entry, rows = _stub_engine()
    _set_bit(rows[0], 7)
    m = degraded_mod.mirror_for_entry(entry, np.array(rows[0]))
    assert isinstance(m, degraded_mod.BitsetMirror)
    assert _bits_of(m.encode(_ROW_UNITS)) == {7}
