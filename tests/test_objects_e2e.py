"""End-to-end public-API tests, run against BOTH engines.

Mirrors the reference's integration-first strategy (SURVEY.md §4: tests
drive the real public API against a live backend) — our two backends are
the TPU pools and the host golden models; parametrizing over both also
proves mode-switch parity (same results either way).
"""

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config


@pytest.fixture(params=["tpu", "host"])
def client(request):
    cfg = Config()
    if request.param == "tpu":
        cfg.use_tpu_sketch(min_bucket=64)
    return redisson_tpu.create(cfg)


def test_bloom_filter_e2e(client):
    bf = client.get_bloom_filter("bf")
    assert bf.try_init(10_000, 0.01) is True
    assert bf.try_init(10_000, 0.01) is False  # tryInit-once semantics
    assert bf.get_size() == 95851  # optimal m for n=1e4, p=0.01
    assert bf.get_hash_iterations() == 7
    assert bf.add("hello") is True
    assert bf.add("hello") is False
    assert bf.contains("hello") is True
    assert bf.contains("goodbye") is False
    keys = [f"k{i}" for i in range(5000)]
    newly = bf.add_all(keys)
    assert newly >= 4990  # all new (tiny chance of in-batch collision)
    assert bf.contains_all(keys) == 5000
    ghosts = [f"ghost{i}" for i in range(5000)]
    fpp = bf.contains_all(ghosts) / 5000
    assert fpp < 0.02
    est = bf.count()
    assert abs(est - 5001) / 5001 < 0.1
    assert bf.is_exists()
    assert bf.delete() is True
    assert not bf.is_exists()
    with pytest.raises(RuntimeError):
        bf.add("x")


def test_bloom_camel_case_aliases(client):
    bf = client.get_bloom_filter("bfc")
    assert bf.tryInit(1000, 0.03) is True
    assert bf.getSize() == bf.get_size()
    bf.add("a")
    assert bf.contains("a")
    assert client.getBloomFilter("bfc").contains("a")


def test_hll_e2e(client):
    h = client.get_hyper_log_log("hll")
    assert h.add("a") is True
    assert h.add("a") is False  # same key: no register change
    h.add_all([f"u{i}" for i in range(30_000)])
    c = h.count()
    assert abs(c - 30_001) / 30_001 < 0.03
    h2 = client.get_hyper_log_log("hll2")
    h2.add_all([f"u{i}" for i in range(20_000, 50_000)])
    union = h.count_with("hll2")
    assert abs(union - 50_001) / 50_001 < 0.03
    h.merge_with("hll2")
    assert abs(h.count() - 50_001) / 50_001 < 0.03
    # count_with must not have mutated h2
    assert abs(h2.count() - 30_000) / 30_000 < 0.03


def test_bitset_e2e(client):
    bs = client.get_bit_set("bs")
    assert bs.get(100) is False
    assert bs.set(100) is False  # previous value
    assert bs.set(100) is True
    assert bs.get(100) is True
    assert bs.flip(101) is True  # new value
    assert bs.flip(101) is False
    assert bs.clear_bit(100) is True
    assert bs.cardinality() == 0
    bs.set_range(10, 500)
    assert bs.cardinality() == 490
    assert bs.length() == 500
    assert bs.first_set_bit() == 10
    assert bs.first_clear_bit() == 0
    bs.clear_range(20, 30)
    assert bs.cardinality() == 480
    # auto-grow
    bs.set(100_000)
    assert bs.get(100_000) is True
    assert bs.cardinality() == 481
    assert bs.length() == 100_001
    # vectorized
    prev = bs.set_many(np.array([7, 7, 8]))
    assert prev.tolist() == [False, True, False]
    vals = bs.get_many(np.array([7, 8, 9, 10**6]))
    assert vals.tolist() == [True, True, False, False]


def test_bitset_bitop(client):
    a = client.get_bit_set("ba")
    b = client.get_bit_set("bb")
    a.set_many(np.array([1, 3, 5]))
    b.set_many(np.array([3, 5, 7]))
    a.and_op("bb")
    assert sorted(np.nonzero(a.as_bit_array())[0].tolist()) == [3, 5]
    a.or_op("bb")
    assert sorted(np.nonzero(a.as_bit_array())[0].tolist()) == [3, 5, 7]
    a.xor_op("bb")
    assert a.cardinality() == 0


def test_cms_e2e(client):
    c = client.get_count_min_sketch("cms")
    assert c.try_init(4, 1 << 12, track_top_k=5) is True
    assert c.try_init(4, 1 << 12) is False
    assert c.add("x") == 1
    assert c.add("x") == 2
    assert c.add("x", count=10) == 12
    assert c.estimate("x") == 12
    assert c.estimate("never-seen") == 0
    # heavy hitters
    stream = ["hot"] * 500 + [f"cold{i}" for i in range(200)]
    rng = np.random.default_rng(1)
    rng.shuffle(stream)
    c.add_all(stream)
    top = c.top_k(1)
    assert top[0][0] == "hot" and top[0][1] >= 500
    # merge
    c2 = client.get_count_min_sketch("cms2")
    c2.try_init(4, 1 << 12)
    c2.add("x", count=5)
    c.merge("cms2")
    assert c.estimate("x") == 17
    c3 = client.get_count_min_sketch("cms3")
    c3.try_init(2, 64)
    with pytest.raises(ValueError):
        c3.merge("cms")


def test_mode_parity_bloom():
    """Same keys through both engines -> identical membership answers
    (identical hash material + formulas), i.e. FPP drift = 0 by design."""
    keys = [f"key:{i}" for i in range(2000)]
    ghosts = [f"ghost:{i}" for i in range(2000)]
    results = {}
    for mode in ("tpu", "host"):
        cfg = Config()
        if mode == "tpu":
            cfg.use_tpu_sketch(min_bucket=64)
        cl = redisson_tpu.create(cfg)
        bf = cl.get_bloom_filter("parity")
        bf.try_init(2000, 0.01)
        bf.add_all(keys)
        results[mode] = (
            np.asarray(bf.contains_each(keys)),
            np.asarray(bf.contains_each(ghosts)),
        )
    np.testing.assert_array_equal(results["tpu"][0], results["host"][0])
    np.testing.assert_array_equal(results["tpu"][1], results["host"][1])


def test_tenant_pool_growth():
    cfg = Config().use_tpu_sketch(min_bucket=64, initial_tenants_per_class=2)
    cl = redisson_tpu.create(cfg)
    bfs = []
    for i in range(5):  # forces pool growth past 2 rows
        bf = cl.get_bloom_filter(f"g{i}")
        bf.try_init(1000, 0.01)
        bf.add_all([f"{i}:{j}" for j in range(100)])
        bfs.append(bf)
    for i, bf in enumerate(bfs):
        assert bf.contains_all([f"{i}:{j}" for j in range(100)]) == 100
        assert bf.contains(f"{(i + 1) % 5}:0") in (True, False)  # sane
        # cross-tenant isolation: other tenants' keys mostly absent
        other = bf.contains_all([f"{(i + 1) % 5}:{j}" for j in range(100)])
        assert other < 10


def test_rename_and_keys(client):
    bf = client.get_bloom_filter("rn1")
    bf.try_init(100, 0.01)
    bf.add("v")
    bf.rename("rn2")
    assert bf.contains("v")
    assert not client.get_bloom_filter("rn1").is_exists()
    assert "rn2" in client.get_sketch_names()
