"""Observability subsystem (ISSUE 1): labeled registry, log2 histograms,
lifecycle spans, SLOWLOG, INFO commandstats/latencystats over a live
RESP connection, Prometheus exposition, and the hot-path overhead guard.
"""

import time

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.codecs import LongCodec
from redisson_tpu.obs import Observability
from redisson_tpu.obs.registry import (
    N_TIME_BUCKETS,
    MetricsRegistry,
    bucket_index_us,
    bucket_upper_bound_us,
)
from redisson_tpu.obs.slowlog import SlowLog
from redisson_tpu.serve.metrics import Metrics, Profiler
from redisson_tpu.serve.resp import RespServer

from test_resp_server import RespClient


# -- histogram buckets ------------------------------------------------------


def test_log2_bucket_boundaries():
    # Boundaries are le = 2^i microseconds: a value EQUAL to a boundary
    # lands in that boundary's bucket, one ulp above rolls over.
    assert bucket_index_us(0.0) == 0
    assert bucket_index_us(1.0) == 0
    assert bucket_index_us(2.0) == 1
    assert bucket_index_us(3.0) == 2
    assert bucket_index_us(4.0) == 2
    assert bucket_index_us(5.0) == 3
    for i in range(1, N_TIME_BUCKETS):
        assert bucket_index_us(float(1 << i)) == i
        assert bucket_index_us(float((1 << i) + 1)) == i + 1 or i + 1 > N_TIME_BUCKETS
    # Beyond the last finite bucket: +Inf.
    assert bucket_index_us(float(1 << 30)) == N_TIME_BUCKETS
    assert bucket_upper_bound_us(N_TIME_BUCKETS) == float("inf")
    assert bucket_upper_bound_us(3) == 8.0


def test_histogram_observe_and_render():
    reg = MetricsRegistry()
    h = reg.histogram("rtpu_test_seconds", "t", ("op",))
    h.observe(("x",), 3e-6)  # 3us -> bucket le=4us
    h.observe(("x",), 3e-6)
    c = h.child(("x",))
    assert c.count == 2
    assert c.buckets[2] == 2 and sum(c.buckets) == 2
    text = reg.render_prometheus()
    assert "# TYPE rtpu_test_seconds histogram" in text
    # Cumulative buckets: the le=4us line carries both observations.
    assert 'rtpu_test_seconds_bucket{op="x",le="4e-06"} 2' in text
    assert 'rtpu_test_seconds_count{op="x"} 2' in text


def test_percentile_edge_cases():
    reg = MetricsRegistry()
    h = reg.histogram("rtpu_p_seconds", "t", ("op",))
    # No samples: all-zero percentiles.
    assert h.percentiles(("x",), (50, 99)) == [0.0, 0.0]
    # n=1: every percentile is that one bucket's upper bound.
    h.observe(("x",), 3e-6)
    p50, p99 = h.percentiles(("x",), (50, 99))
    assert p50 == p99 == 4e-6
    # all-equal: still one bucket, p50 == p99.
    for _ in range(100):
        h.observe(("y",), 100e-6)  # -> le=128us
    p50, p99 = h.percentiles(("y",), (50, 99))
    assert p50 == p99 == 128e-6
    # Mixed: p50 in the low bucket, p99 in the high one.
    for _ in range(98):
        h.observe(("z",), 1e-6)
    for _ in range(2):
        h.observe(("z",), 1000e-6)
    p50, p99 = h.percentiles(("z",), (50, 99))
    assert p50 == 1e-6
    assert p99 == 1024e-6


def test_counter_total_suffix_and_overflow_cap():
    reg = MetricsRegistry()
    c = reg.counter("rtpu_things", "t", ("who",), max_children=4)
    assert c.name == "rtpu_things_total"
    for i in range(10):
        c.inc((f"t{i}",))
    # Cardinality cap: 4 real children, the rest collapse into overflow.
    labels = {lv for lv, _ in c.items()}
    assert len(labels) == 5
    assert ("_overflow",) in labels
    assert c.get(("_overflow",)) == 6


# -- slowlog ----------------------------------------------------------------


def test_slowlog_threshold_and_ring_eviction():
    sl = SlowLog(max_len=3, threshold_us=1000)
    assert not sl.maybe_add(0.0005, [b"GET", b"k"])  # below threshold
    assert len(sl) == 0
    for i in range(5):
        assert sl.maybe_add(0.002, [b"GET", b"k%d" % i])
    assert len(sl) == 3  # ring evicted the two oldest
    entries = sl.entries()
    assert [e.args[1] for e in entries] == [b"k4", b"k3", b"k2"]  # newest 1st
    assert [e.id for e in entries] == [4, 3, 2]  # ids keep increasing
    assert all(e.duration_us >= 1000 for e in entries)
    assert sl.entries(1)[0].id == 4
    sl.reset()
    assert len(sl) == 0
    # threshold < 0 disables logging entirely (Redis semantics).
    sl.set_threshold_us(-1)
    assert not sl.maybe_add(10.0, [b"GET"])


def test_slowlog_arg_truncation():
    sl = SlowLog(max_len=8, threshold_us=0)
    big = b"x" * 500
    sl.maybe_add(0.001, [b"SET", big])
    e = sl.entries()[0]
    assert e.args[1].startswith(b"x" * 128)
    assert e.args[1].endswith(b"... (372 more bytes)")
    sl.maybe_add(0.001, [b"MSET"] + [b"a"] * 40)
    e = sl.entries()[0]
    assert len(e.args) == 32
    assert e.args[-1] == b"... (10 more arguments)"


# -- spans ------------------------------------------------------------------


@pytest.fixture
def tpu_client():
    cfg = Config().set_codec(LongCodec()).use_tpu_sketch(
        batch_window_us=100, min_bucket=64
    )
    cl = redisson_tpu.create(cfg)
    yield cl
    cl.shutdown()


def test_span_phase_sum_matches_end_to_end(tpu_client):
    bf = tpu_client.get_bloom_filter("span-bf")
    bf.try_init(10_000, 0.01)
    bf.add_all(np.arange(512, dtype=np.uint64))
    bf.contains_each(np.arange(512, dtype=np.uint64))
    spans = tpu_client.obs.spans.recent()
    assert spans, "coalesced launches must leave spans"
    for s in spans:
        phases = s.phases()
        # The four lifecycle phases partition the end-to-end latency.
        assert set(phases) == {
            "coalesce_wait", "host_stage", "device_dispatch", "d2h_fetch"
        }
        assert sum(phases.values()) == pytest.approx(
            s.end_to_end(), rel=1e-6, abs=1e-6
        )
        assert s.nops > 0 and not s.error
    # The registry saw the same launches.
    snap = tpu_client.get_metrics()
    assert snap["ops"], snap
    assert any(
        st["ops"] >= 1024 and st["p99_ms"] > 0
        for st in snap["ops"].values()
    ), snap["ops"]
    # Per-tenant dimension.
    assert snap["tenants"].get("span-bf", 0) >= 1024


def test_direct_dispatch_records_ops():
    """coalesce=False (the sharded-engine default test shape) must not
    report zero ops — the executor records through record_dispatch."""
    cfg = Config().set_codec(LongCodec()).use_tpu_sketch(
        coalesce=False, min_bucket=64
    )
    cl = redisson_tpu.create(cfg)
    try:
        bf = cl.get_bloom_filter("d-bf")
        bf.try_init(10_000, 0.01)
        bf.add_all(np.arange(256, dtype=np.uint64))
        snap = cl.get_metrics()
        assert snap["ops_total"] >= 256
        assert snap["batches_total"] >= 1
        # Per-method dispatch counters in the labeled registry.
        fam = cl.obs.registry.family("rtpu_dispatches_total")
        assert sum(c.value for _, c in fam.items()) >= 1
    finally:
        cl.shutdown()


def test_sharded_direct_dispatch_records_ops_and_shards():
    cfg = Config().set_codec(LongCodec()).use_tpu_sketch(
        num_shards=8, coalesce=False, min_bucket=64
    )
    cl = redisson_tpu.create(cfg)
    try:
        bf = cl.get_bloom_filter("sh-bf")
        bf.try_init(10_000, 0.01)
        bf.add_all(np.arange(256, dtype=np.uint64))
        snap = cl.get_metrics()
        assert snap["ops_total"] >= 256, snap
        shard_fam = cl.obs.registry.family("rtpu_shard_ops_total")
        total = sum(c.value for _, c in shard_fam.items())
        assert total >= 256
    finally:
        cl.shutdown()


# -- legacy Metrics fixes (satellites) --------------------------------------


def test_legacy_render_prometheus_counter_types():
    m = Metrics()
    m.record_batch(nops=8, wait_s=0.001, flush_s=0.002)
    text = m.render_prometheus()
    assert "# TYPE redisson_tpu_ops_total counter" in text
    assert "# TYPE redisson_tpu_batches_total counter" in text
    assert "# TYPE redisson_tpu_ops_per_sec gauge" in text
    assert "# TYPE redisson_tpu_p99_wait_ms gauge" in text
    assert "redisson_tpu_ops_total 8" in text


def test_device_memory_reports_all_devices():
    import jax

    mem = Profiler.device_memory()
    assert isinstance(mem, dict)
    # conftest forces 8 virtual CPU devices: every one must be keyed.
    assert len(mem) == len(jax.devices())
    for d in jax.devices():
        assert f"{d.platform}:{d.id}" in mem


# -- RESP wire surface ------------------------------------------------------


@pytest.fixture
def resp():
    cl = redisson_tpu.create(Config())
    srv = RespServer(cl)
    conn = RespClient(srv.host, srv.port)
    yield conn, srv, cl
    srv.close()
    cl.shutdown()


def test_info_commandstats_wire_format(resp):
    conn, srv, cl = resp
    assert conn.cmd("SET", "k", "v") == "OK"
    assert conn.cmd("GET", "k") == b"v"
    conn.cmd("GET", "k")
    with pytest.raises(RuntimeError):
        conn.cmd("EXEC")  # EXEC without MULTI -> counted as failed
    info = conn.cmd("INFO", "commandstats").decode()
    lines = dict(
        line.split(":", 1)
        for line in info.strip().splitlines()
        if ":" in line
    )
    assert "cmdstat_get" in lines and "cmdstat_set" in lines
    get_fields = dict(
        kv.split("=") for kv in lines["cmdstat_get"].split(",")
    )
    assert get_fields["calls"] == "2"
    assert int(get_fields["usec"]) >= 0
    assert float(get_fields["usec_per_call"]) >= 0
    exec_fields = dict(
        kv.split("=") for kv in lines["cmdstat_exec"].split(",")
    )
    assert exec_fields["failed_calls"] == "1"
    # latencystats section exists and carries percentile fields.
    lat = conn.cmd("INFO", "latencystats").decode()
    assert "latency_percentiles_usec_get:p50=" in lat
    # Default INFO excludes commandstats (Redis parity), INFO all includes.
    assert "cmdstat_" not in conn.cmd("INFO").decode()
    assert "cmdstat_" in conn.cmd("INFO", "all").decode()
    # CONFIG RESETSTAT zeroes the section.
    assert conn.cmd("CONFIG", "RESETSTAT") == "OK"
    info = conn.cmd("INFO", "commandstats").decode()
    assert "cmdstat_get" not in info


def test_slowlog_over_resp(resp):
    conn, srv, cl = resp
    assert conn.cmd("SLOWLOG", "LEN") == 0
    assert conn.cmd("SLOWLOG", "GET") == []
    # Default threshold (10ms): a DEBUG SLEEP is slow, a PING is not.
    conn.cmd("PING")
    conn.cmd("DEBUG", "SLEEP", "0.02")
    assert conn.cmd("SLOWLOG", "LEN") == 1
    entries = conn.cmd("SLOWLOG", "GET")
    assert len(entries) == 1
    eid, ts, dur_us, args, addr, name = entries[0]
    assert dur_us >= 10_000
    assert args == [b"DEBUG", b"SLEEP", b"0.02"]
    assert b":" in addr  # client ip:port travels with the entry
    # Threshold 0 logs everything; max-len bounds the ring.
    assert conn.cmd("CONFIG", "SET", "slowlog-log-slower-than", "0") == "OK"
    assert conn.cmd("CONFIG", "SET", "slowlog-max-len", "4") == "OK"
    for i in range(8):
        conn.cmd("PING")
    entries = conn.cmd("SLOWLOG", "GET", "-1")
    assert len(entries) == 4
    ids = [e[0] for e in entries]
    assert ids == sorted(ids, reverse=True)  # newest first
    assert conn.cmd("SLOWLOG", "RESET") == "OK"
    # The RESET itself logs at threshold 0 — Redis does the same.
    assert conn.cmd("SLOWLOG", "LEN") <= 1
    assert any(b"GET [<count>|-1]" in h for h in conn.cmd("SLOWLOG", "HELP"))
    # get_metrics grows the command view without breaking the dict shape.
    snap = cl.get_metrics()
    assert snap["commands"]["PING"]["calls"] >= 9
    assert "slowlog_len" in snap


def test_slowlog_redacts_auth_and_multi_counts_once(resp):
    conn, srv, cl = resp
    assert conn.cmd("CONFIG", "SET", "slowlog-log-slower-than", "0") == "OK"
    # AUTH on a passwordless server errors — but its args must still be
    # redacted in the slowlog (the password was typed either way).
    with pytest.raises(RuntimeError):
        conn.cmd("AUTH", "s3cret-password")
    flat = repr(conn.cmd("SLOWLOG", "GET", "-1"))
    assert "s3cret-password" not in flat
    assert "(redacted)" in flat
    # HELLO ... AUTH user pass: only the credential pair is redacted.
    with pytest.raises(RuntimeError):
        conn.cmd("HELLO", "3", "AUTH", "default", "hello-secret")
    flat = repr(conn.cmd("SLOWLOG", "GET", "-1"))
    assert "hello-secret" not in flat
    # MULTI queue-time must not double-count commandstats: one queued
    # SET executed by EXEC records exactly one SET call.
    assert conn.cmd("CONFIG", "RESETSTAT") == "OK"
    assert conn.cmd("MULTI") == "OK"
    assert conn.cmd("SET", "mk", "mv") == "QUEUED"
    assert conn.cmd("EXEC") == ["OK"]
    stats = cl.get_metrics()["commands"]
    assert stats["SET"]["calls"] == 1, stats
    assert stats["EXEC"]["calls"] == 1
    # Blocking commands: parked time is wait, not work — calls count
    # but no latency sample and no slowlog entry (threshold is 0 here,
    # so ANY recorded duration would enter the ring).
    before = len(cl.obs.slowlog)
    assert conn.cmd("BLPOP", "absent-q", "0.15") is None
    stats = cl.get_metrics()["commands"]
    assert stats["BLPOP"]["calls"] == 1
    assert stats["BLPOP"]["usec"] == 0  # no latency observed
    assert not any(
        e.args and e.args[0] == b"BLPOP"
        for e in cl.obs.slowlog.entries()
    )
    assert len(cl.obs.slowlog) >= before  # other commands still log


# -- prometheus endpoint ----------------------------------------------------


def test_prometheus_labels_and_types(tpu_client):
    srv = RespServer(tpu_client)
    conn = RespClient(srv.host, srv.port)
    try:
        conn.cmd("SET", "k", "v")
        conn.cmd("GET", "k")
        bf = tpu_client.get_bloom_filter("prom-bf")
        bf.try_init(10_000, 0.01)
        bf.add_all(np.arange(256, dtype=np.uint64))
        text = tpu_client.render_prometheus()
        # Per-command labeled series, typed counter with _total suffix.
        assert "# TYPE rtpu_resp_commands_total counter" in text
        assert 'rtpu_resp_commands_total{cmd="GET"} 1' in text
        # Per-tenant labeled series.
        assert "# TYPE rtpu_tenant_ops_total counter" in text
        assert 'tenant="prom-bf"' in text
        # Phase histograms are real histogram families.
        assert "# TYPE rtpu_op_phase_seconds histogram" in text
        assert 'phase="device_dispatch"' in text
        # Executor health gauges typed gauge.
        assert "# TYPE rtpu_coalescer_queued_ops gauge" in text
        assert "# TYPE rtpu_tenants gauge" in text
        assert 'rtpu_tenants{kind="bloom"} 1' in text
        assert "# TYPE rtpu_pool_rows gauge" in text
        # Legacy aggregate rides along with corrected types.
        assert "# TYPE redisson_tpu_ops_total counter" in text
    finally:
        conn.close()
        srv.close()


def test_metrics_http_endpoint(tpu_client):
    import http.client

    bf = tpu_client.get_bloom_filter("http-bf")
    bf.try_init(10_000, 0.01)
    bf.add_all(np.arange(64, dtype=np.uint64))
    srv = tpu_client.start_metrics_endpoint()
    assert tpu_client.start_metrics_endpoint() is srv  # one shared server
    with pytest.raises(RuntimeError):  # conflicting rebind must not be
        tpu_client.start_metrics_endpoint(port=srv.port + 1)  # silent
    conn = http.client.HTTPConnection(srv.host, srv.port, timeout=10)
    conn.request("GET", "/metrics")
    resp = conn.getresponse()
    assert resp.status == 200
    assert resp.getheader("Content-Type").startswith("text/plain")
    body = resp.read().decode()
    assert "rtpu_tenant_ops_total" in body
    assert "redisson_tpu_ops_total" in body
    conn.request("GET", "/nope")
    assert conn.getresponse().status == 404
    conn.close()


# -- metric-catalog doc sync (ISSUE 13 satellite) ---------------------------


def test_metric_catalog_matches_doc():
    """docs/observability.md's labeled-registry table is CANONICAL:
    every family/gauge a fully-featured engine registers must appear in
    the table, and every table row must exist in the registry — the
    catalog can never drift again (it was missing the PR 10-12
    families when ISSUE 13 landed)."""
    import os

    doc_path = os.path.join(
        os.path.dirname(__file__), "..", "docs", "observability.md"
    )
    with open(doc_path) as f:
        doc = f.read()
    # Rows of the "Labeled registry" table only (the legacy aggregate
    # table and prose mentions don't count).
    section = doc.split("### Labeled registry", 1)[1]
    section = section.split("\n## ", 1)[0]
    doc_names = set()
    for line in section.splitlines():
        m = __import__("re").match(r"\|\s*`(rtpu_[a-z0-9_]+)`", line)
        if m:
            doc_names.add(m.group(1))
    assert doc_names, "doc table parse found no rows"

    # A fully-featured engine: coalescer + prewarmer + journal gauges.
    cfg = Config().set_codec(LongCodec()).use_tpu_sketch(
        min_bucket=64, prewarm=True
    )
    cl = redisson_tpu.create(cfg)
    try:
        reg = cl.obs.registry
        registered = set(reg._families)
        registered |= {name for name, _, _, _ in reg._callbacks}
    finally:
        cl.shutdown()

    # Load-attribution families (ISSUE 16): pinned by name so a rename
    # that dodges the generic diff below still fails loudly here.
    assert {
        "rtpu_tenant_device_us_total", "rtpu_loadmap_slot_ops",
        "rtpu_loadmap_sampled_keys", "rtpu_loadmap_tracked_keys",
    } <= registered

    missing_from_doc = registered - doc_names
    assert not missing_from_doc, (
        f"families registered but absent from the "
        f"docs/observability.md table: {sorted(missing_from_doc)}"
    )
    stale_in_doc = doc_names - registered
    assert not stale_in_doc, (
        f"doc table rows with no registered family: "
        f"{sorted(stale_in_doc)}"
    )


def test_spanrecorder_public_reset():
    """Satellite 6: the bench lifecycle reset is a PUBLIC SpanRecorder
    surface — no more reaching into ``spans._phase_hist`` privates."""
    obs = Observability()
    s = obs.spans.start("op-x", 8)
    s.stamp("d2h_fetch")
    s.finish()
    assert obs.spans.recent()
    assert obs.spans._total_hist.items()
    obs.spans.reset()
    assert obs.spans.recent() == []
    assert not obs.spans._total_hist.items()
    assert not obs.spans._ops.items()
    # Observability.reset_op_stats delegates to it (bench call site).
    s2 = obs.spans.start("op-y", 1)
    s2.stamp("d2h_fetch")
    s2.finish()
    obs.reset_op_stats()
    assert obs.spans.recent() == []


# -- overhead guard ---------------------------------------------------------


@pytest.mark.slow
def test_metrics_overhead_under_ten_percent():
    """Hot-path guard (ISSUE 1 acceptance): op submit through an
    instrumented engine path must be ≤10% slower than through a no-op
    metrics stub.

    Measured at the exact instrumentation the hot producer path pays:
    ``coalescer.submit`` with a span-recording obs bundle and a tenant
    label riding every submit (per-tenant accounting defers to the
    completer thread), against the identical calls with obs disabled.
    A long batch window keeps the flush thread parked, so the timing
    covers submit alone rather than GIL contention with dispatch;
    rounds interleave A/B with GC paused and compare MINIMA (the
    noise-free intrinsic cost)."""
    import gc

    from redisson_tpu.executor.coalescer import BatchCoalescer

    class _Lazy:
        def __init__(self, v):
            self._v = v

        def result(self):
            return self._v

    def dispatch(cols):
        return _Lazy(np.concatenate(cols))

    arr = np.arange(64, dtype=np.int64)
    N = 2000

    def make(obs):
        # Window >> test duration and max_batch > N*64: nothing flushes
        # while the timed loop runs (drained at shutdown).
        return BatchCoalescer(
            batch_window_us=30_000_000, max_batch=1 << 22,
            max_queued_ops=1 << 24, obs=obs,
        )

    def round_time(c, tenant):
        t0 = time.perf_counter()
        for _ in range(N):
            c.submit(("op",), dispatch, (arr,), 64, tenant=tenant)
        return time.perf_counter() - t0

    def measure():
        plain, instrumented = [], []
        coalescers = []
        gc.disable()
        try:
            for r in range(12):
                ca, cb = make(None), make(Observability())
                coalescers += [ca, cb]
                # Warm both paths' allocator/lock state before timing,
                # then alternate A/B order per round so bursty load on a
                # shared box can't systematically tax one arm.
                round_time(ca, None)
                round_time(cb, "bench-tenant")
                if r % 2 == 0:
                    plain.append(round_time(ca, None))
                    instrumented.append(round_time(cb, "bench-tenant"))
                else:
                    instrumented.append(round_time(cb, "bench-tenant"))
                    plain.append(round_time(ca, None))
        finally:
            gc.enable()
            for c in coalescers:
                c.shutdown()
        return plain, instrumented

    # External load only ever INFLATES a sample, so the intrinsic
    # overhead is bounded by the cleanest observation: min of per-round
    # PAIRED ratios (adjacent measurements share any load burst), with a
    # few attempts to find a quiet window.
    history = []
    for _ in range(4):
        plain, instrumented = measure()
        ratio = min(q / p for p, q in zip(plain, instrumented))
        ratio = min(ratio, min(instrumented) / min(plain))
        history.append(ratio)
        if ratio <= 1.10:
            return
    raise AssertionError(f"instrumented submit >10% slower: {history}")


@pytest.mark.slow
def test_trace_off_overhead_under_five_percent():
    """ISSUE 13 overhead guard, same harness as the ≤10% guard above:
    with sampling OFF, the trace hooks on the submit path must cost
    ≤5% over the same path with the trace module stubbed out entirely.

    The stub arm replaces the coalescer's ``_trace`` module with a
    bare ``ENABLED = False`` namespace — identical flag-read cost, but
    any future regression that does REAL work on the off path (calling
    current(), minting contexts, taking locks) shows up only in the
    live arm and trips the ratio."""
    import gc

    from redisson_tpu.executor import coalescer as co_mod
    from redisson_tpu.executor.coalescer import BatchCoalescer

    assert not co_mod._trace.ENABLED, (
        "a tracer leaked an armed sample rate into this test"
    )

    class _Lazy:
        def __init__(self, v):
            self._v = v

        def result(self):
            return self._v

    def dispatch(cols):
        return _Lazy(np.concatenate(cols))

    class _Stub:
        ENABLED = False

    arr = np.arange(64, dtype=np.int64)
    N = 2000

    def make():
        return BatchCoalescer(
            batch_window_us=30_000_000, max_batch=1 << 22,
            max_queued_ops=1 << 24, obs=Observability(),
        )

    def round_time(c):
        t0 = time.perf_counter()
        for _ in range(N):
            c.submit(("op",), dispatch, (arr,), 64, tenant="t")
        return time.perf_counter() - t0

    def measure():
        live, stubbed = [], []
        coalescers = []
        real = co_mod._trace
        gc.disable()
        try:
            for r in range(12):
                ca, cb = make(), make()
                coalescers += [ca, cb]
                round_time(ca)
                round_time(cb)
                if r % 2 == 0:
                    co_mod._trace = real
                    live.append(round_time(ca))
                    co_mod._trace = _Stub
                    stubbed.append(round_time(cb))
                else:
                    co_mod._trace = _Stub
                    stubbed.append(round_time(cb))
                    co_mod._trace = real
                    live.append(round_time(ca))
        finally:
            co_mod._trace = real
            gc.enable()
            for c in coalescers:
                c.shutdown()
        return stubbed, live

    history = []
    for _ in range(4):
        stubbed, live = measure()
        ratio = min(q / p for p, q in zip(stubbed, live))
        ratio = min(ratio, min(live) / min(stubbed))
        history.append(ratio)
        if ratio <= 1.05:
            return
    raise AssertionError(
        f"sampling-off tracing >5% over stubbed hooks: {history}"
    )
