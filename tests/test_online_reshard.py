"""Online reshard + failure monitor (round 4, VERDICT #4): live
change_topology without restart/wipe, zero lost writes under concurrent
traffic; FailureMonitor surfaces dead shards as typed events."""

import threading

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.codecs import LongCodec
from redisson_tpu.serve.nodes import FailureMonitor, NodeDownEvent, NodeUpEvent


def _client(**kw):
    kw.setdefault("min_bucket", 64)
    kw.setdefault("batch_window_us", 300)
    cfg = Config().set_codec(LongCodec()).use_tpu_sketch(**kw)
    return redisson_tpu.create(cfg)


def test_reshard_1_to_4_preserves_all_object_kinds():
    c = _client()
    try:
        bf = c.get_bloom_filter("rs-bf")
        bf.try_init(10_000, 0.01)
        keys = np.arange(2000, dtype=np.uint64)
        bf.add_all(keys)
        h = c.get_hyper_log_log("rs-hll")
        h.add_all(np.arange(5000, dtype=np.uint64))
        hll_before = h.count()
        bs = c.get_bit_set("rs-bs")
        idx = np.array([1, 77, 4095, 12345], dtype=np.uint32)
        bs.set_many(idx)
        bits_before = bs.as_bit_array()
        cms = c.get_count_min_sketch("rs-cms")
        cms.try_init(4, 1 << 12)
        cms.add_all(np.arange(100, dtype=np.uint64), np.full(100, 3))

        assert c.change_topology(4) is True
        assert getattr(c._engine.executor, "S", 1) == 4

        assert int(np.sum(bf.contains_each(keys))) == len(keys)
        assert h.count() == hll_before  # register-exact remap
        assert np.array_equal(bs.as_bit_array(), bits_before)
        assert cms.estimate(np.uint64(5)) >= 3

        # And back down to a single device.
        assert c.change_topology(1) is True
        assert int(np.sum(bf.contains_each(keys))) == len(keys)
        assert h.count() == hll_before
        assert np.array_equal(bs.as_bit_array(), bits_before)
        assert c.change_topology(1) is False  # no-op
    finally:
        c.shutdown()


def test_reshard_under_concurrent_traffic_zero_lost_writes():
    """VERDICT #4 done-criterion: reshard 1→4 while producers keep
    writing; every acknowledged add must be present afterwards."""
    c = _client()
    try:
        n_threads = 4
        bfs = []
        for t in range(n_threads):
            bf = c.get_bloom_filter(f"cc-{t}")
            bf.try_init(50_000, 0.01)
            bfs.append(bf)
        errors = []
        acked = [[] for _ in range(n_threads)]
        stop = threading.Event()

        def producer(tid):
            bf = bfs[tid]
            base = tid << 32
            i = 0
            try:
                while not stop.is_set() and i < 8000:
                    ks = np.arange(base + i, base + i + 64, dtype=np.uint64)
                    bf.add_all_async(ks).result(timeout=120)
                    acked[tid].append((base + i, base + i + 64))
                    i += 64
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [
            threading.Thread(target=producer, args=(t,), daemon=True)
            for t in range(n_threads)
        ]
        for t in threads:
            t.start()
        import time

        time.sleep(0.5)  # let traffic build
        assert c.change_topology(4) is True
        time.sleep(0.5)  # traffic continues on the new topology
        stop.set()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        assert all(acked[t] for t in range(n_threads)), "no traffic flowed"
        for t in range(n_threads):
            for lo, hi in acked[t]:
                ks = np.arange(lo, hi, dtype=np.uint64)
                got = int(np.sum(bfs[t].contains_each(ks)))
                assert got == hi - lo, (t, lo, hi, got)
    finally:
        c.shutdown()


def test_reshard_drops_replicas_but_keeps_reads_correct():
    c = _client(num_shards=2)
    try:
        bf = c.get_bloom_filter("rep")
        bf.try_init(5000, 0.01)
        keys = np.arange(500, dtype=np.uint64)
        bf.add_all(keys)
        assert bf.set_replicated() is True
        assert bf.is_replicated()
        assert c.change_topology(4) is True
        assert not bf.is_replicated()  # placement was per-old-shard
        assert int(np.sum(bf.contains_each(keys))) == len(keys)
        assert bf.set_replicated() is True  # re-replicate on the new mesh
        assert int(np.sum(bf.contains_each(keys))) == len(keys)
    finally:
        c.shutdown()


def test_failure_monitor_emits_typed_events():
    c = _client()
    try:
        mon = c.get_failure_monitor()
        events = []
        mon.add_listener(events.append)

        class _DeadNode:
            shard = 0
            address = "cpu:0"

            def ping(self, timeout=None):
                return False

        class _LiveNode:
            shard = 0
            address = "cpu:0"

            def ping(self, timeout=None):
                return True

        class _FakeGroup:
            def __init__(self):
                self.nodes = [_DeadNode()]

            def get_nodes(self):
                return self.nodes

        mon._ng = _FakeGroup()
        evs = mon.check_once()
        assert len(evs) == 1 and isinstance(evs[0], NodeDownEvent)
        assert mon.down_shards() == {0}
        assert mon.check_once() == []  # once per transition, not per ping
        mon._ng.nodes = [_LiveNode()]
        evs = mon.check_once()
        assert len(evs) == 1 and isinstance(evs[0], NodeUpEvent)
        assert events and isinstance(events[0], NodeDownEvent)
        assert mon.down_shards() == set()
    finally:
        c.shutdown()


def test_change_topology_failure_rolls_back():
    """A failed swap (more shards than devices) must leave the engine
    fully on the old topology — config, executor, pools."""
    c = _client()
    try:
        bf = c.get_bloom_filter("rb")
        bf.try_init(1000, 0.01)
        bf.add_all(np.arange(100, dtype=np.uint64))
        with pytest.raises(RuntimeError, match="devices"):
            c.change_topology(64)  # CPU mesh has 8
        assert c._engine.config.tpu_sketch.num_shards == 1
        assert getattr(c._engine.executor, "S", 1) == 1
        assert int(np.sum(bf.contains_each(np.arange(100, dtype=np.uint64)))) == 100
        # And a valid reshard still works afterwards.
        assert c.change_topology(4) is True
        assert int(np.sum(bf.contains_each(np.arange(100, dtype=np.uint64)))) == 100
    finally:
        c.shutdown()


def test_reshard_quarantines_replica_rows():
    """Replica rows must NOT return to the free list (in-flight ops may
    target them) — they stay written with the filter's data."""
    c = _client(num_shards=2)
    try:
        bf = c.get_bloom_filter("q")
        bf.try_init(5000, 0.01)
        bf.add_all(np.arange(200, dtype=np.uint64))
        assert bf.set_replicated()
        entry = c._engine.registry.lookup("q")
        replica_rows = [r for r in entry.replica_rows if r != entry.row]
        assert replica_rows
        assert c.change_topology(4) is True
        assert entry.replica_rows is None
        pool = entry.pool
        for r in replica_rows:
            assert r not in pool._free, "replica row was freed into the pool"
        # Quarantined rows still hold the data (an in-flight read targeting
        # them must see correct bits): check via raw row readback.
        row_data = c._engine.executor.read_row(pool, entry.row)
        for r in replica_rows:
            assert np.array_equal(
                c._engine.executor.read_row(pool, r), row_data
            )
    finally:
        c.shutdown()


def test_bitset_writes_survive_concurrent_size_class_migration():
    """Lost-update regression: coalesced bitset sets racing an auto-grow
    (size-class migration) must all land — flush-time row resolution."""
    c = _client(batch_window_us=2000)
    try:
        bs = c.get_bit_set("grow")
        bs.set(10)  # small size class
        errors = []
        acked = []
        stop = threading.Event()

        def writer():
            i = 0
            try:
                while not stop.is_set() and i < 3000:
                    bs.set(100 + i)  # stays within the small class range
                    acked.append(100 + i)
                    i += 1
            except Exception as e:  # pragma: no cover
                errors.append(e)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        import time

        time.sleep(0.05)
        # Trigger migrations to successively larger size classes mid-storm.
        bs.set(5_000)
        bs.set(50_000)
        bs.set(500_000)
        stop.set()
        t.join(timeout=60)
        assert not errors, errors
        assert acked
        arr = bs.as_bit_array()
        missing = [i for i in acked if not arr[i]]
        assert not missing, f"{len(missing)} acknowledged sets lost: {missing[:5]}"
        assert arr[5_000] and arr[50_000] and arr[500_000]
    finally:
        c.shutdown()


def test_retired_executor_forwards_or_raises_typed():
    """A caller that captured the pre-swap executor must NOT run the old
    kernel against the re-laid-out state: plain dispatches forward
    transparently to the successor; runs-metadata dispatches (whose
    successor implementation would be layout-wrong) raise the typed
    retryable error for the coalescer's retry loop."""
    from redisson_tpu.executor.failures import ExecutorRetiredError

    c = _client()
    try:
        bf = c.get_bloom_filter("ret")
        bf.try_init(1000, 0.01)
        bf.add_all(np.arange(10, dtype=np.uint64))
        old_exec = c._engine.executor
        entry = c._engine.registry.lookup("ret")
        m = entry.params["size"]
        k = entry.params["hash_iterations"]
        assert c.change_topology(2) is True
        # Plain dispatch: forwards to the successor (correct answer, no
        # spurious failure for non-coalesced callers).
        assert int(old_exec.bloom_count(entry.pool, entry.row, m, k).result()) > 0
        # Runs-metadata dispatch: sharded successor can't run it — typed
        # retryable so the coalescer re-binds and re-checks support.
        with pytest.raises(ExecutorRetiredError):
            old_exec.bloom_mixed_keys_runs(
                entry.pool, k, np.zeros((1, 2), np.uint32), np.uint32(8),
                np.array([entry.row], np.int32), np.array([m], np.uint32),
                np.array([True]), np.array([0, 1], np.int32),
            )
        # The live path keeps working end-to-end.
        assert int(np.sum(bf.contains_each(np.arange(10, dtype=np.uint64)))) == 10
    finally:
        c.shutdown()


def test_failure_monitor_restart_after_stop():
    c = _client()
    try:
        mon = c.get_failure_monitor(interval_s=0.05)
        mon.start()
        mon.stop()
        mon.start()  # must actually resume sweeping (stop event cleared)
        import time

        time.sleep(0.3)
        assert mon._thread is not None and mon._thread.is_alive()
        mon.stop()
    finally:
        c.shutdown()


def test_failure_monitor_real_devices_ping_ok():
    c = _client()
    try:
        mon = c.get_failure_monitor()
        assert mon.check_once() == []  # healthy devices emit nothing
        assert mon.down_shards() == set()
    finally:
        c.shutdown()
