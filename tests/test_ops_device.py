"""Device kernels vs golden NumPy models — the §4 'golden CPU model' gate.

Runs on the CPU backend (8 virtual devices, see conftest); the same kernels
run unmodified on the real TPU chip.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from redisson_tpu.ops import bitops, bloom, bitset, cms, golden, hll
from redisson_tpu.utils import hashing


def _keys_hashes(n, seed, m=None):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 1 << 63, size=n, dtype=np.uint64)
    blocks, lengths = hashing.encode_uint64_batch(keys)
    if m is None:
        return hashing.murmur3_x86_128(blocks, lengths)
    h1, h2 = hashing.hash128_np(blocks, lengths)
    return hashing.km_reduce_mod(h1, h2, m)


def _pool(T, W, seed=None):
    """Flat word pool with scratch slot, optionally random content."""
    if seed is None:
        return jnp.zeros((T * W + 1,), jnp.uint32)
    rng = np.random.default_rng(seed)
    arr = rng.integers(0, 1 << 32, size=T * W + 1, dtype=np.uint32)
    return jnp.asarray(arr)


class TestBloom:
    M = 1 << 16
    K = 7
    W = (1 << 16) // 32

    def test_add_contains_vs_golden(self):
        T = 4
        pool = _pool(T, self.W)
        g = [golden.GoldenBloomFilter(self.M, self.K) for _ in range(T)]
        rng = np.random.default_rng(5)
        for step in range(3):
            n = 500
            h1m, h2m = _keys_hashes(n, 100 + step, m=self.M)
            rows = rng.integers(0, T, size=n).astype(np.int32)
            pool, newly = bloom.bloom_add(
                pool, jnp.asarray(rows), jnp.asarray(h1m), jnp.asarray(h2m),
                m=self.M, k=self.K, words_per_row=self.W,
            )
            # Golden: per-tenant sequential adds in arrival order.
            newly_g = np.zeros(n, bool)
            for t in range(T):
                sel = rows == t
                newly_g[sel] = g[t].add_hashed(h1m[sel], h2m[sel])
            np.testing.assert_array_equal(np.asarray(newly), newly_g)
            got = bloom.bloom_contains(
                pool, jnp.asarray(rows), jnp.asarray(h1m), jnp.asarray(h2m),
                m=self.M, k=self.K, words_per_row=self.W,
            )
            assert np.asarray(got).all()
        # Unpacked bit-level equality per tenant.
        words = np.asarray(pool)[:-1].reshape(T, self.W)
        for t in range(T):
            dev_bits = np.unpackbits(
                words[t].view(np.uint8), bitorder="little"
            ).astype(bool)
            np.testing.assert_array_equal(dev_bits, g[t].bits)

    def test_duplicate_keys_in_batch_sequential_semantics(self):
        pool = _pool(1, self.W)
        h1m = np.array([123, 123, 456], np.uint32)
        h2m = np.array([77, 77, 99], np.uint32)
        rows = jnp.zeros((3,), jnp.int32)
        pool, newly = bloom.bloom_add(
            pool, rows, jnp.asarray(h1m), jnp.asarray(h2m),
            m=self.M, k=self.K, words_per_row=self.W,
        )
        assert np.asarray(newly).tolist() == [True, False, True]

    def test_padding_mask_no_perturbation(self):
        pool = _pool(1, self.W)
        # One valid op plus padded ops aimed at (0,0) — the word a real op
        # with h1m=0 would hit.
        h1m = jnp.asarray(np.array([0, 0, 0], np.uint32))
        h2m = jnp.asarray(np.array([1, 0, 0], np.uint32))
        valid = jnp.asarray(np.array([True, False, False]))
        pool2, newly = bloom.bloom_add(
            pool, jnp.zeros((3,), jnp.int32), h1m, h2m,
            m=self.M, k=self.K, words_per_row=self.W, valid=valid,
        )
        assert bool(newly[0])
        # Only the valid op's k bits are set (k distinct bits, h2=1).
        total = int(np.asarray(
            bloom.bloom_cardinality(pool2, 0, m=self.M, k=self.K, words_per_row=self.W)
        ))
        assert total == self.K
        # Scratch word may have been written; real words must not include
        # bits from the padded (h1=0, h2=0) ops beyond the valid op's.
        g = golden.GoldenBloomFilter(self.M, self.K)
        g.add_hashed(np.array([0], np.uint32), np.array([1], np.uint32))
        dev_bits = np.unpackbits(
            np.asarray(pool2)[:-1].view(np.uint8), bitorder="little"
        ).astype(bool)
        np.testing.assert_array_equal(dev_bits, g.bits)

    def test_cardinality_estimate(self):
        n = 2000
        m = golden.optimal_num_of_bits(n, 0.01)
        k = golden.optimal_num_of_hash_functions(n, m)
        W = -(-m // 32)
        pool = _pool(1, W)
        h1m, h2m = _keys_hashes(n, 7, m=m)
        pool, _ = bloom.bloom_add(
            pool, jnp.zeros((n,), jnp.int32), jnp.asarray(h1m), jnp.asarray(h2m),
            m=m, k=k, words_per_row=W,
        )
        x = int(np.asarray(bloom.bloom_cardinality(pool, 0, m=m, k=k, words_per_row=W)))
        import math
        est = round(-m / k * math.log(1 - x / m))
        assert abs(est - n) / n < 0.05


class TestHll:
    def test_rank_device_vs_golden(self):
        c0, c1, c2, _ = _keys_hashes(4096, 11)
        # Include edge cases: zero lanes.
        c1 = np.concatenate([c1, np.zeros(4, np.uint32)])
        c2 = np.concatenate([c2, np.array([0, 1 << 14, (1 << 14) - 1, 0xFFFFFFFF], np.uint32)])
        c0 = np.concatenate([c0, np.zeros(4, np.uint32)])
        gi, gr = golden.hll_index_rank(c0, c1, c2)
        di, dr = hll.hll_index_rank_device(jnp.asarray(c0), jnp.asarray(c1), jnp.asarray(c2))
        np.testing.assert_array_equal(np.asarray(di), gi.astype(np.int32))
        np.testing.assert_array_equal(np.asarray(dr), gr)

    def test_add_count_merge_vs_golden(self):
        T = 3
        flat = jnp.zeros((T * golden.HLL_M + 1,), jnp.uint8)
        g = [golden.GoldenHyperLogLog() for _ in range(T)]
        rng = np.random.default_rng(13)
        for step in range(2):
            n = 20000
            c0, c1, c2, _ = _keys_hashes(n, 200 + step)
            rows = rng.integers(0, T, size=n).astype(np.int32)
            flat = hll.hll_add(flat, jnp.asarray(rows), jnp.asarray(c0), jnp.asarray(c1), jnp.asarray(c2))
            for t in range(T):
                sel = rows == t
                g[t].add_hashed(c0[sel], c1[sel], c2[sel])
        regs = np.asarray(flat)[:-1].reshape(T, golden.HLL_M)
        for t in range(T):
            np.testing.assert_array_equal(regs[t], g[t].regs)
            hist = np.asarray(hll.hll_histogram(flat, t))
            est = golden.ertl_estimate(hist)
            assert int(round(est)) == g[t].count()
        # Device-side estimator close to golden float64 one.
        dev_est = float(np.asarray(hll.ertl_estimate_device(jnp.asarray(
            np.asarray(hll.hll_histogram(flat, 0))))))
        assert abs(dev_est - g[0].count()) / max(g[0].count(), 1) < 1e-3
        # Merge rows 1,2 into 0.
        src = jnp.asarray(regs[1:3])
        flat = hll.hll_merge_rows(flat, 0, src)
        g[0].merge(g[1], g[2])
        np.testing.assert_array_equal(
            np.asarray(flat)[: golden.HLL_M], g[0].regs
        )

    def test_histograms_all_matches_per_row(self):
        T = 4
        rng = np.random.default_rng(3)
        regs2d = rng.integers(0, 52, size=(T, golden.HLL_M), dtype=np.uint8)
        flat = jnp.concatenate([jnp.asarray(regs2d).reshape(-1), jnp.zeros((1,), jnp.uint8)])
        all_h = np.asarray(hll.hll_histograms_all(jnp.asarray(regs2d)))
        for t in range(T):
            np.testing.assert_array_equal(
                all_h[t], np.asarray(hll.hll_histogram(flat, t))
            )


class TestBitSet:
    W = 64  # 2048 bits per row

    def test_set_get_clear_flip_vs_golden(self):
        T = 2
        nbits = self.W * 32
        pool = _pool(T, self.W)
        g = [golden.GoldenBitSet(nbits) for _ in range(T)]
        rng = np.random.default_rng(21)
        for step in range(3):
            n = 300
            idx = rng.integers(0, nbits, size=n).astype(np.uint32)
            rows = rng.integers(0, T, size=n).astype(np.int32)
            pool, prev = bitset.bitset_set(
                pool, jnp.asarray(rows), jnp.asarray(idx), words_per_row=self.W
            )
            prev_g = np.zeros(n, bool)
            for t in range(T):
                sel = rows == t
                prev_g[sel] = g[t].set(idx[sel])
            np.testing.assert_array_equal(np.asarray(prev), prev_g)
        # flips with deliberate duplicates
        idx = np.array([5, 5, 5, 9, 9], np.uint32)
        rows = np.zeros(5, np.int32)
        pool, prev = bitset.bitset_flip(
            pool, jnp.asarray(rows), jnp.asarray(idx), words_per_row=self.W
        )
        b5, b9 = bool(g[0].bits[5]), bool(g[0].bits[9])
        assert np.asarray(prev).tolist() == [b5, not b5, b5, b9, not b9]
        g[0].bits[5] = not b5  # net odd flips
        # 9 flipped twice -> unchanged
        # clear batch
        pool, prev = bitset.bitset_clear(
            pool, jnp.asarray(rows[:2]), jnp.asarray(np.array([5, 5], np.uint32)),
            words_per_row=self.W,
        )
        assert np.asarray(prev).tolist() == [bool(g[0].bits[5]), False]
        g[0].bits[5] = False
        words = np.asarray(pool)[:-1].reshape(T, self.W)
        for t in range(T):
            dev_bits = np.unpackbits(words[t].view(np.uint8), bitorder="little").astype(bool)
            np.testing.assert_array_equal(dev_bits, g[t].bits)
            assert int(np.asarray(bitset.bitset_cardinality(pool, t, words_per_row=self.W))) == g[t].cardinality()
            assert int(np.asarray(bitset.bitset_length(pool, t, words_per_row=self.W))) == g[t].length()

    def test_range_set_and_bitpos(self):
        pool = _pool(1, self.W)
        pool = bitset.bitset_set_range(pool, 0, 33, 1000, words_per_row=self.W)
        card = int(np.asarray(bitset.bitset_cardinality(pool, 0, words_per_row=self.W)))
        assert card == 1000 - 33
        assert int(np.asarray(bitset.bitset_bitpos(pool, 0, words_per_row=self.W, target_bit=1))) == 33
        assert int(np.asarray(bitset.bitset_bitpos(pool, 0, words_per_row=self.W, target_bit=0))) == 0
        # clear a sub-range
        pool = bitset.bitset_set_range(pool, 0, 100, 200, words_per_row=self.W, value=False)
        card = int(np.asarray(bitset.bitset_cardinality(pool, 0, words_per_row=self.W)))
        assert card == (1000 - 33) - 100
        # full-word boundaries
        pool2 = bitset.bitset_set_range(_pool(1, self.W), 0, 64, 128, words_per_row=self.W)
        words = np.asarray(pool2)[:-1]
        assert words[2] == 0xFFFFFFFF and words[3] == 0xFFFFFFFF
        assert words[1] == 0 and words[4] == 0

    def test_bitop(self):
        pool = _pool(4, self.W, seed=9)
        words = np.asarray(pool)[:-1].reshape(4, self.W)
        src = jnp.asarray(words[1:3])
        for op, fn in [("and", np.bitwise_and), ("or", np.bitwise_or), ("xor", np.bitwise_xor)]:
            out = bitset.bitset_bitop(pool, 0, src, words_per_row=self.W, op=op)
            np.testing.assert_array_equal(
                np.asarray(out)[: self.W], fn(words[1], words[2])
            )
        out = bitset.bitset_bitop(pool, 0, src[:1], words_per_row=self.W, op="not")
        np.testing.assert_array_equal(np.asarray(out)[: self.W], ~words[1])

    def test_empty_row_length_and_bitpos(self):
        pool = _pool(1, self.W)
        assert int(np.asarray(bitset.bitset_length(pool, 0, words_per_row=self.W))) == 0
        assert int(np.asarray(bitset.bitset_bitpos(pool, 0, words_per_row=self.W, target_bit=1))) == -1


class TestCms:
    D, Wd = 4, 1 << 12

    def test_update_estimate_vs_golden(self):
        T = 2
        cells = self.D * self.Wd
        flat = jnp.zeros((T * cells + 1,), jnp.uint32)
        gold = np.zeros((T, self.D, self.Wd), np.uint64)
        rng = np.random.default_rng(31)
        n = 5000
        # Zipf-ish stream with repeats
        keys = rng.zipf(1.3, size=n).astype(np.uint64) % 500
        blocks, lengths = hashing.encode_uint64_batch(keys)
        h1, h2 = hashing.hash128_np(blocks, lengths)
        h1w, h2w = hashing.km_reduce_mod(h1, h2, self.Wd)
        rows = rng.integers(0, T, size=n).astype(np.int32)
        w1 = np.ones(n, np.uint32)
        flat = cms.cms_update(
            flat, jnp.asarray(rows), jnp.asarray(h1w), jnp.asarray(h2w),
            jnp.asarray(w1), d=self.D, w=self.Wd, cells_per_row=self.D * self.Wd,
        )
        for r in range(self.D):
            idx = (h1w.astype(np.uint64) + np.uint64(r) * h2w.astype(np.uint64)) % np.uint64(self.Wd)
            np.add.at(gold, (rows, np.full(n, r), idx.astype(np.int64)), 1)
        np.testing.assert_array_equal(
            np.asarray(flat)[:-1].reshape(T, self.D, self.Wd), gold.astype(np.uint32)
        )
        est = np.asarray(cms.cms_estimate(
            flat, jnp.asarray(rows), jnp.asarray(h1w), jnp.asarray(h2w),
            d=self.D, w=self.Wd, cells_per_row=self.D * self.Wd,
        ))
        gold_est = gold[rows[:, None], np.arange(self.D)[None, :],
                        np.stack([(h1w.astype(np.uint64) + np.uint64(r) * h2w.astype(np.uint64)) % np.uint64(self.Wd)
                                  for r in range(self.D)], axis=1).astype(np.int64)].min(axis=1)
        np.testing.assert_array_equal(est, gold_est.astype(np.uint32))
        # CMS guarantee: estimate >= true count; with w >> distinct keys,
        # estimates for a key equal its true frequency almost surely.
        true = np.bincount(keys.astype(np.int64), minlength=500)
        per_key_est = {}
        for i in range(n):
            per_key_est[(rows[i], int(keys[i]))] = int(est[i])
        for (t, kk), e in per_key_est.items():
            tc = int(np.sum((keys == kk) & (rows == t)))
            assert e >= tc

    def test_merge_linearity(self):
        cells = self.D * self.Wd
        flat = jnp.zeros((2 * cells + 1,), jnp.uint32)
        h1w = np.array([5, 9], np.uint32)
        h2w = np.array([3, 11], np.uint32)
        flat = cms.cms_update(flat, jnp.asarray(np.array([0, 1], np.int32)),
                              jnp.asarray(h1w), jnp.asarray(h2w),
                              jnp.ones((2,), jnp.uint32), d=self.D, w=self.Wd, cells_per_row=self.D * self.Wd)
        src = np.asarray(flat)[cells:2 * cells].reshape(1, cells)
        merged = cms.cms_merge_rows(flat, 0, jnp.asarray(src), cells_per_row=cells)
        est = np.asarray(cms.cms_estimate(
            merged, jnp.asarray(np.array([0, 0], np.int32)),
            jnp.asarray(h1w), jnp.asarray(h2w), d=self.D, w=self.Wd, cells_per_row=self.D * self.Wd,
        ))
        assert est.tolist() == [1, 1]
