"""Overload control plane (ISSUE 7): end-to-end deadlines, admission
control, per-tenant fair load shedding, and slow-client protection.

Covers the tentpole invariants —

- deadlines ride RESP ingress / the direct-API scope into the coalescer
  and shed expired work strictly PRE-dispatch (fast DeadlineExceededError
  instead of the old 120 s hang);
- parked-backoff segments whose every op expired are dropped with their
  futures resolved;
- admission control fails a deadline-carrying submit fast when the
  estimated queue wait exceeds the residual budget (blocking stays the
  no-deadline default), drivable deterministically via the
  ``overload.pressure`` chaos point;
- the tenant governor sheds over-quota tenants first (token bucket +
  in-flight quota) and never touches within-quota tenants;
- acked writes are never shed (differential soak under fault injection);
- the RESP server sheds at ingress past the watermark, disconnects slow
  clients at the output-buffer limits, live-applies every overload knob
  via CONFIG SET with bounds validation, and reports INFO overload.
"""

import socket
import threading
import time

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config, chaos
from redisson_tpu.executor.coalescer import BatchCoalescer, HintedFuture
from redisson_tpu.executor.failures import (
    DeadlineExceededError,
    DispatchTimeoutError,
    TenantThrottledError,
)
from redisson_tpu.obs import Observability
from redisson_tpu.serve.resp import RespServer
from redisson_tpu.tenancy.registry import TenantGovernor
from redisson_tpu import overload


@pytest.fixture(autouse=True)
def _chaos_off():
    chaos.clear()
    chaos.reset_counts()
    yield
    chaos.clear()
    chaos.reset_counts()


def make_client(**tpu_kw):
    from redisson_tpu.client import RedissonTpuClient

    tpu_kw.setdefault("batch_window_us", 100)
    tpu_kw.setdefault("min_bucket", 64)
    # Keep breakers out of the way unless a test wants them: these
    # tests drive sustained fault injection and a surprise degradation
    # would change which layer answers.
    tpu_kw.setdefault("breaker_failure_threshold", 10_000)
    cfg = Config().use_tpu_sketch(**tpu_kw)
    cfg.retry_attempts = 2
    cfg.retry_interval_ms = 5
    return RedissonTpuClient(cfg)


class _FakeLazy:
    def __init__(self, value):
        self._v = value

    def result(self):
        return self._v


class _BlockingLazy:
    def __init__(self, gate, value):
        self._gate = gate
        self._v = value

    def result(self):
        self._gate.wait(10.0)
        return self._v


class _FakeHealth:
    def __init__(self):
        self.failures = []

    def allow_dispatch(self, op):
        return True

    def record_failure(self, op, exc=None):
        self.failures.append((op, exc))

    def record_success(self, op):
        pass


# -- deadline scope ----------------------------------------------------------


class TestDeadlineScope:
    def test_nesting_inner_wins_and_restores(self):
        assert overload.current_deadline() is None
        with overload.deadline_scope(10.0):
            outer = overload.current_deadline()
            assert outer is not None
            with overload.deadline_scope(0.5):
                assert overload.current_deadline() < outer
            assert overload.current_deadline() == outer
        assert overload.current_deadline() is None

    def test_none_frame_shadows_outer(self):
        with overload.deadline_scope(1.0):
            with overload.deadline_scope(None):
                assert overload.current_deadline() is None
            assert overload.current_deadline() is not None

    def test_thread_isolation(self):
        seen = []
        with overload.deadline_scope(5.0):
            t = threading.Thread(
                target=lambda: seen.append(overload.current_deadline())
            )
            t.start()
            t.join()
        assert seen == [None]


# -- tenant governor ---------------------------------------------------------


class TestTenantGovernor:
    def test_rate_limit_sheds_over_quota_only(self):
        clock = [0.0]
        g = TenantGovernor(rate_limit=100.0, burst=100.0,
                           clock=lambda: clock[0])
        g.admit("a", 100)  # burst drained
        with pytest.raises(TenantThrottledError) as ei:
            g.admit("a", 1)
        assert ei.value.reason == "rate"
        # Another tenant is untouched by a's exhaustion.
        g.admit("b", 100)
        # Refill: 0.5 s at 100 ops/s -> 50 tokens.
        clock[0] = 0.5
        g.admit("a", 50)
        with pytest.raises(TenantThrottledError):
            g.admit("a", 1)

    def test_full_bucket_admits_oversize_with_debt(self):
        clock = [0.0]
        g = TenantGovernor(rate_limit=10.0, burst=20.0,
                           clock=lambda: clock[0])
        g.admit("a", 500)  # full bucket: admitted, tokens go negative
        with pytest.raises(TenantThrottledError):
            g.admit("a", 1)  # deep in debt
        clock[0] = 60.0  # debt (-480) repaid at 10/s, then some
        g.admit("a", 1)

    def test_inflight_quota_and_release(self):
        g = TenantGovernor(max_inflight=10)
        g.admit("a", 8)
        with pytest.raises(TenantThrottledError) as ei:
            g.admit("a", 4)
        assert ei.value.reason == "inflight"
        g.release("a", 8)
        g.admit("a", 10)

    def test_inflight_oversize_single_submit_admitted_when_idle(self):
        """A bulk op larger than the quota is admitted when the tenant
        has nothing in flight (the token-bucket / coalescer-queue
        carve-out) — it must not be unserviceable at any retry rate."""
        g = TenantGovernor(max_inflight=100)
        g.admit("a", 512)  # oversize, idle tenant: admitted
        with pytest.raises(TenantThrottledError):
            g.admit("a", 1)  # now over quota: throttled
        g.release("a", 512)
        g.admit("a", 512)

    def test_set_limits_live(self):
        g = TenantGovernor()
        assert not g.active
        g.admit("a", 10_000)  # inactive: everything passes
        g.set_limits(rate_limit=1.0, burst=1.0)
        assert g.active
        g.admit("a", 1)
        with pytest.raises(TenantThrottledError):
            g.admit("a", 1)

    def test_disable_reenable_inflight_does_not_leak(self):
        """A disable/re-enable cycle must not strand in-flight charges:
        release() is skipped while the quota is off, so set_limits
        resets the charge table — otherwise the tenant is throttled
        forever once re-enabled."""
        g = TenantGovernor(max_inflight=1000)
        g.admit("a", 500)
        g.set_limits(max_inflight=0)  # live-disable; the 500 never release
        g.admit("a", 10_000)  # off: passes
        g.set_limits(max_inflight=400)  # re-enable, clean slate
        g.admit("a", 400)
        # A stale release from the pre-disable ops clamps at zero.
        g.release("a", 500)
        g.release("a", 500)
        g.admit("a", 400)


# -- coalescer: deadlines + admission ---------------------------------------


def _mk(**kw):
    kw.setdefault("batch_window_us", 200)
    kw.setdefault("max_batch", 1024)
    return BatchCoalescer(**kw)


def _cols(n=8):
    return (np.arange(n, dtype=np.int64),)


def test_expired_deadline_sheds_at_submit():
    c = _mk()
    try:
        with pytest.raises(DeadlineExceededError) as ei:
            c.submit(("k",), lambda cols: _FakeLazy(cols[0]), _cols(), 8,
                     deadline=time.monotonic() - 0.01)
        assert ei.value.stage == "submit"
    finally:
        c.shutdown()


def test_admission_sheds_on_pressure_bias():
    """The overload.pressure chaos point inflates the wait estimate
    deterministically: a deadline-carrying submit sheds fast, a
    no-deadline submit still queues and completes (blocking stays the
    default)."""
    chaos.inject("overload.pressure", kind="pressure", rate=1.0,
                 latency_s=30.0)
    c = _mk()
    try:
        with pytest.raises(DeadlineExceededError) as ei:
            c.submit(("k",), lambda cols: _FakeLazy(cols[0]), _cols(), 8,
                     deadline=time.monotonic() + 1.0)
        assert ei.value.stage == "admission"
        fut = c.submit(("k",), lambda cols: _FakeLazy(cols[0]), _cols(), 8)
        assert HintedFuture(fut, c).result(timeout=10.0) is not None
    finally:
        chaos.clear()
        c.shutdown()


def test_queued_segment_expired_is_shed_pre_dispatch():
    """A segment stuck behind a slow launch whose deadline lapses is
    shed without ever dispatching; the op ahead is untouched."""
    gate = threading.Event()
    b_dispatched = []

    def slow(cols):
        gate.wait(10.0)
        return _FakeLazy(np.concatenate(cols) if len(cols) > 1 else cols[0])

    def fast(cols):
        b_dispatched.append(1)
        return _FakeLazy(cols[0])

    c = _mk(batch_window_us=100)
    try:
        fa = c.submit(("a",), slow, _cols(), 8)
        time.sleep(0.05)  # let the flush thread enter slow()
        fb = c.submit(("b",), fast, _cols(), 8,
                      deadline=time.monotonic() + 0.15)
        time.sleep(0.4)  # deadline lapses while 'a' blocks the loop
        gate.set()
        with pytest.raises(DeadlineExceededError) as ei:
            HintedFuture(fb, c).result(timeout=5.0)
        assert ei.value.stage == "queue"
        assert not b_dispatched  # shed strictly pre-dispatch
        assert HintedFuture(fa, c).result(timeout=5.0) is not None
    finally:
        gate.set()
        c.shutdown()


def test_parked_backoff_all_expired_dropped_fast():
    """Satellite: a parked (retry-backoff) segment whose every op
    expired must be dropped with futures resolved — not wait out the
    backoff, not burn the remaining retry budget."""
    def dispatch(cols):
        raise RuntimeError("transient")

    c = _mk(retry_attempts=10, retry_interval_s=5.0,
            retry_max_backoff_s=5.0)
    try:
        fut = c.submit(("k",), dispatch, _cols(), 8,
                       deadline=time.monotonic() + 0.25)
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceededError):
            fut.result(timeout=10.0)
        # Without the parked-expired drop this resolves only after the
        # ~5 s backoff (x10 attempts); with it, right at the deadline.
        assert time.monotonic() - t0 < 2.0
    finally:
        c.shutdown()


def test_fetch_timeout_from_config_records_breaker_failure():
    """Satellite: the hardcoded 120 s default is gone — a no-deadline
    .result() is bounded by fetch_timeout_s, and tripping it records a
    breaker failure + rtpu_fetch_timeouts like other completion
    failures."""
    gate = threading.Event()
    health = _FakeHealth()
    obs = Observability()
    c = _mk(fetch_timeout_s=0.2, health=health, obs=obs)
    try:
        fut = c.submit(
            ("bloom_mix",), lambda cols: _BlockingLazy(gate, cols[0]),
            _cols(), 8,
        )
        hf = HintedFuture(fut, c, op="bloom_mix")
        t0 = time.monotonic()
        with pytest.raises(DispatchTimeoutError):
            hf.result()
        assert time.monotonic() - t0 < 2.0
        assert health.failures and health.failures[0][0] == "bloom_mix"
        assert sum(
            int(cv.value) for _, cv in obs.fetch_timeouts.items()
        ) == 1
    finally:
        gate.set()
        c.shutdown()


def test_deadline_bounded_wait_is_not_a_device_failure():
    """A result wait cut short by the op's own deadline raises
    DeadlineExceededError and does NOT feed the breaker — overload is
    not device failure."""
    gate = threading.Event()
    health = _FakeHealth()
    c = _mk(fetch_timeout_s=30.0, health=health)
    try:
        dl = time.monotonic() + 0.15
        fut = c.submit(
            ("k",), lambda cols: _BlockingLazy(gate, cols[0]), _cols(), 8,
            deadline=dl,
        )
        hf = HintedFuture(fut, c, deadline=dl, op="k")
        with pytest.raises(DeadlineExceededError) as ei:
            hf.result()
        assert ei.value.stage == "fetch_wait"
        assert not health.failures
    finally:
        gate.set()
        c.shutdown()


def test_no_deadline_submit_still_blocks_at_queue_bound():
    """Blocking backpressure remains the no-deadline default (the
    pre-overload contract: test_backpressure.py's invariant)."""
    gate = threading.Event()

    def dispatch(cols):
        gate.wait(5.0)
        return _FakeLazy(np.concatenate(cols) if len(cols) > 1 else cols[0])

    c = _mk(max_queued_ops=64, max_inflight=1)
    try:
        # Key "a" pops into the gated dispatch (flush thread blocked);
        # key "b" stays QUEUED, holding the bound (same-key submits
        # would join one segment and pop together, emptying the queue).
        futs = [c.submit(("a",), dispatch, _cols(32), 32)]
        time.sleep(0.1)  # let the flush thread enter dispatch
        futs.append(c.submit(("b",), dispatch, _cols(40), 40))
        done = threading.Event()

        def producer():
            futs.append(c.submit(("c",), dispatch, _cols(64), 64))
            done.set()

        threading.Thread(target=producer, daemon=True).start()
        assert not done.wait(0.3)  # blocked, not shed
        gate.set()
        assert done.wait(5.0)
        for f in futs:
            HintedFuture(f, c).result(timeout=5.0)
    finally:
        gate.set()
        c.shutdown()


def test_deadline_bounded_queue_wait_sheds_instead_of_blocking():
    gate = threading.Event()

    def dispatch(cols):
        gate.wait(5.0)
        return _FakeLazy(np.concatenate(cols) if len(cols) > 1 else cols[0])

    c = _mk(max_queued_ops=64, max_inflight=1)
    try:
        c.submit(("a",), dispatch, _cols(32), 32)
        time.sleep(0.1)  # flush thread now parked inside dispatch
        c.submit(("b",), dispatch, _cols(40), 40)  # queued: bound held
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceededError) as ei:
            c.submit(("c",), dispatch, _cols(64), 64,
                     deadline=time.monotonic() + 0.2)
        assert ei.value.stage == "queue"
        assert time.monotonic() - t0 < 2.0
    finally:
        gate.set()
        c.shutdown()


# -- engine level: deadline x chaos ------------------------------------------


class TestEngineDeadlines:
    def test_injected_latency_converts_to_fast_deadline_error(self):
        """Satellite: injected latency at dispatch.* + an op deadline
        must surface as a FAST DeadlineExceededError, not a 120 s
        hang."""
        client = make_client()
        try:
            bf = client.get_bloom_filter("dl")
            bf.try_init(10_000, 0.01)
            keys = np.arange(32, dtype=np.uint64)
            bf.add_all_async(keys).result(timeout=60.0)  # warm/compile
            chaos.inject("dispatch", kind="latency", rate=1.0, seed=1,
                         latency_s=0.5)
            t0 = time.monotonic()
            with pytest.raises(DeadlineExceededError):
                with client.op_deadline(100):
                    bf.contains_all_async(keys).result()
            assert time.monotonic() - t0 < 5.0
            chaos.clear()
            # The engine recovers: same op, no deadline, succeeds.
            assert bf.contains_all(keys) == len(keys)
        finally:
            chaos.clear()
            client.shutdown()

    def test_acked_writes_never_shed_differential(self):
        """Satellite soak: under fault injection + deadlines, every
        write the caller saw acked is present afterwards (shedding is
        strictly pre-dispatch)."""
        client = make_client()
        try:
            bf = client.get_bloom_filter("acked")
            bf.try_init(50_000, 0.01)
            bf.add_all_async(
                np.array([10**9], dtype=np.uint64)
            ).result(timeout=60.0)  # warm/compile
            chaos.inject("dispatch", kind="error", rate=0.4, seed=7)
            acked, shed = [], 0
            for i in range(60):
                keys = np.arange(i * 8, i * 8 + 8, dtype=np.uint64)
                try:
                    with client.op_deadline(500):
                        fut = bf.add_all_async(keys)
                    fut.result()
                    acked.append(keys)
                except Exception:
                    shed += 1
            chaos.clear()
            assert acked, "soak produced no acked writes"
            for keys in acked:
                assert bf.contains_all(keys) == len(keys), (
                    "acked write lost under shedding"
                )
        finally:
            chaos.clear()
            client.shutdown()

    def test_tenant_governor_sheds_burster_not_victim(self):
        """Over-quota tenants shed first: the bursting tenant trips
        TenantThrottledError while the within-quota tenant never
        does."""
        client = make_client(tenant_rate_limit=1_000,
                             tenant_burst_ops=500)
        try:
            victim = client.get_bloom_filter("victim")
            victim.try_init(10_000, 0.01)
            burster = client.get_bloom_filter("burster")
            burster.try_init(10_000, 0.01)
            keys = np.arange(32, dtype=np.uint64)
            victim.add_all_async(keys).result(timeout=60.0)  # warm
            burst_shed = 0
            for _ in range(8):  # 8 x 1024 ops back-to-back >> the quota
                try:
                    burster.add_all_async(
                        np.arange(1024, dtype=np.uint64)
                    ).result()
                except TenantThrottledError:
                    burst_shed += 1
                # Victim trickles well under its own rate, mid-burst.
                victim.contains_all_async(keys).result()
            assert burst_shed > 0
            snap = client._engine.governor.stats()
            assert snap["throttled_ops"] > 0
        finally:
            client.shutdown()


@pytest.mark.slow
def test_fairness_soak_victim_keeps_throughput():
    """Fairness soak: a within-quota tenant retains most of its solo
    throughput while a co-tenant bursts far over the rate limit (the
    bench's config7 fairness claim, in miniature)."""
    client = make_client(tenant_rate_limit=4_000, tenant_burst_ops=2_000,
                         max_queued_ops=1 << 14)
    try:
        victim = client.get_bloom_filter("victim")
        victim.try_init(50_000, 0.01)
        burster = client.get_bloom_filter("burster")
        burster.try_init(50_000, 0.01)
        keys = np.arange(50, dtype=np.uint64)
        victim.add_all_async(keys).result(timeout=60.0)
        burster.add_all_async(keys).result(timeout=60.0)

        def victim_rate(duration_s):
            # Paced at ~1000 ops/s: a quarter of the tenant quota.
            chunks = 0
            t_end = time.perf_counter() + duration_s
            while time.perf_counter() < t_end:
                victim.contains_all_async(keys).result()
                chunks += 1
                time.sleep(0.05)
            return chunks / duration_s

        solo = victim_rate(1.5)

        stop = threading.Event()

        def burst():
            while not stop.is_set():
                try:
                    burster.add_all_async(
                        np.arange(512, dtype=np.uint64)
                    ).result()
                except Exception:
                    time.sleep(0.001)  # shed fast-path: don't spin hot

        t = threading.Thread(target=burst, daemon=True)
        t.start()
        try:
            contested = victim_rate(1.5)
        finally:
            stop.set()
            t.join(timeout=10.0)
        # The bench asserts >= 0.8 on quiet hardware; the test keeps a
        # generous margin for CI noise while still catching a collapse.
        assert contested >= 0.5 * solo, (solo, contested)
    finally:
        client.shutdown()


# -- RESP server --------------------------------------------------------------


class _Resp:
    """Minimal RESP2 wire client (the test_resp_server idiom)."""

    def __init__(self, host, port, timeout=10):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self._buf = b""

    def cmd(self, *args):
        out = b"*" + str(len(args)).encode() + b"\r\n"
        for a in args:
            if not isinstance(a, bytes):
                a = str(a).encode()
            out += b"$" + str(len(a)).encode() + b"\r\n" + a + b"\r\n"
        self.sock.sendall(out)
        return self._read()

    def _recv(self):
        data = self.sock.recv(65536)
        if not data:
            raise ConnectionError("closed")
        self._buf += data

    def _line(self):
        while b"\r\n" not in self._buf:
            self._recv()
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read(self):
        line = self._line()
        t, body = line[:1], line[1:]
        if t == b"+":
            return body.decode()
        if t == b"-":
            raise RuntimeError(body.decode())
        if t == b":":
            return int(body)
        if t == b"$":
            n = int(body)
            if n < 0:
                return None
            while len(self._buf) < n + 2:
                self._recv()
            out, self._buf = self._buf[:n], self._buf[n + 2:]
            return out
        if t == b"*":
            n = int(body)
            return None if n < 0 else [self._read() for _ in range(n)]
        raise RuntimeError(f"bad reply {t!r}")

    def close(self):
        self.sock.close()


@pytest.fixture
def served():
    client = make_client()
    server = RespServer(client)
    conn = _Resp(server.host, server.port)
    yield client, server, conn
    conn.close()
    server.close()
    client.shutdown()


class TestRespOverload:
    def test_client_deadline_admission_shed_and_clear(self, served):
        client, server, conn = served
        conn.cmd("BF.RESERVE", "f", "0.01", "1000")
        conn.cmd("BF.ADD", "f", "warm")  # compile outside the window
        chaos.inject("overload.pressure", kind="pressure", rate=1.0,
                     latency_s=30.0)
        assert conn.cmd("CLIENT", "DEADLINE") == b"default"
        assert conn.cmd("CLIENT", "DEADLINE", "50") == "OK"
        assert conn.cmd("CLIENT", "DEADLINE") == b"50"
        with pytest.raises(RuntimeError, match="BUSY.*deadline"):
            conn.cmd("BF.ADD", "f", "x")
        # CLIENT DEADLINE 0: no deadline -> no admission check -> flows.
        assert conn.cmd("CLIENT", "DEADLINE", "0") == "OK"
        assert conn.cmd("BF.ADD", "f", "x") in (0, 1)
        chaos.clear()

    def test_default_op_deadline_from_config(self):
        client = make_client(op_deadline_ms=50)
        server = RespServer(client)
        conn = _Resp(server.host, server.port)
        try:
            # Warm (first-touch compile) outlives a 50 ms deadline by
            # design — run it with the per-connection override off,
            # then revert to the server default.
            conn.cmd("CLIENT", "DEADLINE", "0")
            conn.cmd("BF.RESERVE", "f", "0.01", "1000")
            conn.cmd("BF.ADD", "f", "warm")
            conn.cmd("CLIENT", "DEADLINE", "-1")
            chaos.inject("overload.pressure", kind="pressure", rate=1.0,
                         latency_s=30.0)
            with pytest.raises(RuntimeError, match="BUSY.*deadline"):
                conn.cmd("BF.ADD", "f", "x")
        finally:
            chaos.clear()
            conn.close()
            server.close()
            client.shutdown()

    def test_ingress_watermark_sheds_nonexempt_only(self, served):
        client, server, conn = served
        conn.cmd("SET", "k", "v")
        c = client._engine.coalescer
        server.admission_watermark = 0.5
        # Simulate a deep queue (white-box: pressure reads _queued_ops;
        # the idle flush thread won't touch a fabricated count with no
        # segments queued).  Must dwarf the default max_queued_ops
        # (8 x max_batch = 512k) to cross the watermark.
        c._queued_ops += 1_000_000
        try:
            with pytest.raises(RuntimeError, match="BUSY.*overloaded"):
                conn.cmd("GET", "k")
            with pytest.raises(RuntimeError, match="BUSY.*overloaded"):
                conn.cmd("BF.ADD", "f", "x")
            # Exempt: the operator can still see and fix the overload.
            assert conn.cmd("PING") == "PONG"
            assert b"overload_pressure" in conn.cmd("INFO", "overload")
            assert conn.cmd("CONFIG", "GET", "admission-watermark")
            # MULTI/EXEC cannot bypass the door: queueing is free, the
            # transaction is judged (and consumed) at EXEC.
            assert conn.cmd("MULTI") == "OK"
            assert conn.cmd("SET", "k", "w") == "QUEUED"
            with pytest.raises(RuntimeError, match="BUSY.*transaction"):
                conn.cmd("EXEC")
            with pytest.raises(RuntimeError, match="without MULTI"):
                conn.cmd("EXEC")  # consumed: EXECABORT-style, not queued
        finally:
            c._queued_ops -= 1_000_000
        assert conn.cmd("GET", "k") == b"v"  # the shed SET never ran

    def test_config_set_validation_and_live_apply(self, served):
        client, server, conn = served
        # Nonsense is rejected before anything applies.
        for key, bad in (
            ("op-deadline-ms", "-5"),
            ("admission-watermark", "0"),
            ("admission-watermark", "-0.5"),
            ("admission-watermark", "1.5"),
            ("fetch-timeout-ms", "0"),
            ("tenant-rate-limit", "-1"),
            ("client-output-buffer-limit", "-1"),
            ("client-output-buffer-soft-seconds", "-2"),
            ("op-deadline-ms", "abc"),
        ):
            with pytest.raises(RuntimeError):
                conn.cmd("CONFIG", "SET", key, bad)
        # Valid values apply live, to the right layer.
        # Fractional rates are legal (the governor takes floats): the
        # validator must be exactly as wide as the setter.
        assert conn.cmd(
            "CONFIG", "SET", "tenant-rate-limit", "0.5"
        ) == "OK"
        assert client._engine.governor.rate_limit == 0.5
        assert conn.cmd(
            "CONFIG", "SET", "op-deadline-ms", "250",
            "admission-watermark", "0.75",
            "fetch-timeout-ms", "30000",
            "tenant-rate-limit", "5000",
            "tenant-max-inflight", "4096",
            "client-output-buffer-limit", "65536",
            "client-output-buffer-soft-seconds", "2.5",
        ) == "OK"
        assert server.op_deadline_ms == 250
        assert server.admission_watermark == 0.75
        assert client._engine.coalescer.fetch_timeout_s == 30.0
        gov = client._engine.governor
        assert gov.rate_limit == 5000 and gov.max_inflight == 4096
        assert server.output_buffer_limit == 65536
        assert server.output_buffer_soft_seconds == 2.5
        got = conn.cmd("CONFIG", "GET", "op-deadline-ms")
        assert got == [b"op-deadline-ms", b"250"]

    def test_info_overload_section(self, served):
        _client, _server, conn = served
        info = conn.cmd("INFO", "overload").decode()
        for key in (
            "overload_op_deadline_ms", "overload_admission_watermark",
            "overload_pressure", "overload_est_wait_us",
            "overload_shed_ops", "overload_deadline_exceeded",
            "overload_tenant_throttled", "overload_fetch_timeouts",
            "overload_slow_client_disconnects",
            "overload_output_buffer_limit",
        ):
            assert key in info, key
        # Default INFO includes the section too.
        assert "# Overload" in conn.cmd("INFO").decode()

    def test_slow_client_disconnected_at_output_buffer_limit(self, served):
        client, server, conn = served
        big = b"x" * (4 << 20)
        conn.cmd("SET", "big", big)
        assert conn.cmd(
            "CONFIG", "SET", "client-output-buffer-limit", "8192",
            "client-output-buffer-soft-seconds", "1",
        ) == "OK"
        # A client that requests a huge reply and never reads: the
        # server's bounded send must disconnect it instead of parking
        # the connection thread forever.
        lazy = socket.create_connection(
            (server.host, server.port), timeout=10
        )
        lazy.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 16384)
        lazy.sendall(b"*2\r\n$3\r\nGET\r\n$3\r\nbig\r\n")
        deadline = time.monotonic() + 10.0
        killed = False
        while time.monotonic() < deadline:
            if server._slow_client_kills > 0:
                killed = True
                break
            time.sleep(0.05)
        assert killed, "slow client was not disconnected"
        lazy.close()
        info = conn.cmd("INFO", "overload").decode()
        assert "overload_slow_client_disconnects:0" not in info
        # A well-behaved client still gets the big value under the same
        # limits (progress resets the stall clock).
        assert conn.cmd("GET", "big") == big

    def test_hard_only_limit_still_disconnects_underlimit_stall(self):
        """With ONLY the hard byte limit set (soft-seconds 0), a stall
        whose pending remainder is UNDER the limit must still fall back
        to the socket's own timeout — not loop forever holding the
        connection thread (the legacy sendall died under idle_timeout)."""
        client = make_client()
        server = RespServer(client, idle_timeout_s=1.0)
        conn = _Resp(server.host, server.port)
        try:
            conn.cmd("SET", "big", b"x" * (4 << 20))
            assert conn.cmd(
                "CONFIG", "SET",
                "client-output-buffer-limit", str(64 << 20),
            ) == "OK"
            lazy = socket.create_connection(
                (server.host, server.port), timeout=10
            )
            lazy.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 16384)
            lazy.sendall(b"*2\r\n$3\r\nGET\r\n$3\r\nbig\r\n")
            deadline = time.monotonic() + 10.0
            while (
                server._slow_client_kills == 0
                and time.monotonic() < deadline
            ):
                time.sleep(0.05)
            assert server._slow_client_kills > 0
            lazy.close()
        finally:
            conn.close()
            server.close()
            client.shutdown()

    def test_fast_clients_unaffected_by_buffer_limits(self, served):
        _client, server, conn = served
        conn.cmd("CONFIG", "SET", "client-output-buffer-limit", "4096",
                 "client-output-buffer-soft-seconds", "1")
        conn.cmd("SET", "k", "v" * 100_000)
        for _ in range(5):
            assert len(conn.cmd("GET", "k")) == 100_000
        assert server._slow_client_kills == 0


# -- direct-dispatch deadlines (ROADMAP overload item (c), ISSUE 8) ----------


class TestDirectDispatchDeadlines:
    def test_expired_deadline_sheds_before_direct_dispatch(self):
        """With no coalescer in front (coalesce=False) the dispatch
        lock IS the queue: an expired op must shed strictly
        PRE-dispatch in the _locked wrapper, exactly like the
        coalescer's sweep — previously it dispatched regardless."""
        client = make_client(coalesce=False)
        try:
            bf = client.get_bloom_filter("direct-dl")
            bf.try_init(10_000, 0.01)
            warm = np.arange(16, dtype=np.uint64)
            late = np.arange(100, 116, dtype=np.uint64)
            bf.add_all(warm)
            with overload.deadline_scope(at=time.monotonic() - 0.01):
                with pytest.raises(DeadlineExceededError) as ei:
                    bf.add_all(late)
            assert ei.value.stage == "direct"
            # Strictly pre-dispatch: the shed write never reached the
            # device, earlier acked writes are untouched.
            assert bf.contains_all(late) == 0
            assert bf.contains_all(warm) == len(warm)
            obs = client._engine.obs
            assert obs.deadline_exceeded.get(("direct",)) >= len(late)
            assert obs.shed_ops.get(("deadline",)) >= len(late)
            # Without a deadline the same op proceeds (recovery).
            bf.add_all(late)
            assert bf.contains_all(late) == len(late)
        finally:
            client.shutdown()

    def test_direct_reads_shed_too(self):
        client = make_client(coalesce=False)
        try:
            bf = client.get_bloom_filter("direct-dl-read")
            bf.try_init(10_000, 0.01)
            keys = np.arange(16, dtype=np.uint64)
            bf.add_all(keys)
            with overload.deadline_scope(at=time.monotonic() - 0.01):
                with pytest.raises(DeadlineExceededError):
                    bf.contains_all(keys)
        finally:
            client.shutdown()

    def test_row_maintenance_exempt_mid_compound_op(self):
        """delete()'s detach->zero->free must not tear apart when a
        deadline lapses mid-compound: read/write/zero_row are exempt
        from the direct shed (a detached-but-unzeroed row could be
        reallocated carrying stale bits)."""
        client = make_client(coalesce=False)
        try:
            bf = client.get_bloom_filter("direct-maint")
            bf.try_init(10_000, 0.01)
            bf.add_all(np.arange(8, dtype=np.uint64))
            with overload.deadline_scope(at=time.monotonic() - 0.01):
                assert client._engine.delete("direct-maint") is True
            # The row was actually zeroed: a successor under the name
            # starts empty.
            bf2 = client.get_bloom_filter("direct-maint")
            bf2.try_init(10_000, 0.01)
            assert bf2.contains_all(np.arange(8, dtype=np.uint64)) == 0
        finally:
            client.shutdown()


# -- admission estimator x link phase (ROADMAP overload item (a), ISSUE 8) ---


def test_admission_estimator_tracks_link_phase_flip():
    """merge_cap()'s put-RT EWMA corrects the admission estimate in
    BOTH directions around a link-phase flip, against synthetic
    retirement samples (no wall-clock dependence):

    - fast->slow: the retire EWMA still says 5 ms/launch while the
      put-RT signal already says ~0.5 s — the estimate must be floored
      by the put RT instead of over-admitting into a half-second queue;
    - slow->fast: the retire EWMA is still slow-poisoned while genuine
      fast retirements pulled the put RT under fast_launch_s — the
      estimate must be capped so healthy traffic stops being shed."""
    c = _mk(max_batch=64, max_inflight=8)
    try:
        c._service_ewma_s = 0.005  # fast-phase retire history
        c._ops_per_launch_ewma = 8.0
        with c._lock:
            c._queued_ops = 64  # 8 launches queued ahead
        assert c.estimate_wait_s() < 0.1
        # Link flips slow: three ~0.5 s retirements flip the put-RT
        # EWMA past slow_launch_s (slow samples always count, even
        # non-genuine ones) while the retire EWMA is untouched.
        for _ in range(3):
            c._release_launch_slot(0.5, genuine=False)
        assert c._put_rt_ewma > c.slow_launch_s
        est_slow = c.estimate_wait_s()
        assert est_slow > 0.5, est_slow  # floored by the phase signal
        with pytest.raises(DeadlineExceededError) as ei:
            c.submit(("k",), lambda cols: _FakeLazy(cols[0]), _cols(), 8,
                     deadline=time.monotonic() + 0.2)
        assert ei.value.stage == "admission"
        # Flip back fast: genuine fast retirements pull the put RT
        # under fast_launch_s within a few launches; the retire EWMA
        # stays slow-poisoned (forced), but the cap stops the shed.
        c._service_ewma_s = 1.0
        for _ in range(8):
            c._release_launch_slot(0.01, genuine=True)
        assert c._put_rt_ewma < c.fast_launch_s
        est_fast = c.estimate_wait_s()
        assert est_fast <= c.slow_launch_s, est_fast
        fut = c.submit(("k",), lambda cols: _FakeLazy(cols[0]), _cols(), 8,
                       deadline=time.monotonic() + 5.0)
        assert HintedFuture(fut, c).result(timeout=10.0) is not None
    finally:
        c.shutdown()


def test_phase_service_neutral_between_thresholds():
    """Between the fast/slow thresholds the put-RT signal is
    ambiguous: the retire EWMA stands unmodified (no correction
    flapping in the gray zone)."""
    c = _mk()
    try:
        c._service_ewma_s = 0.02
        c._put_rt_ewma = 0.1  # between fast (0.08) and slow (0.25)
        assert c._phase_service_s() == 0.02
        # And a zeroed signal (no launches yet) leaves the base alone.
        c._put_rt_ewma = 0.0
        assert c._phase_service_s() == 0.02
    finally:
        c.shutdown()


def test_replication_fence_shadows_ambient_deadline():
    """Review finding (PR 8): the replication fence's redispatch
    COMPLETES a write already applied to the primary row, so it must
    run under an explicit no-deadline frame — a caller deadline that
    lapsed during the first dispatch must not shed the broadcast
    (diverged replicas, reads rotating across copies would flap)."""
    client = make_client(coalesce=False)
    try:
        bf = client.get_bloom_filter("fence")
        bf.try_init(10_000, 0.01)
        keys = np.arange(32, dtype=np.uint64)
        eng = client._engine
        entry = eng.registry.lookup("fence")
        entry.replica_rows = [entry.row]  # publish: the fence must fire
        seen = []

        def redispatch():
            seen.append(overload.current_deadline())
            bf.add_all(keys)  # real non-exempt direct dispatch

        with overload.deadline_scope(at=time.monotonic() - 0.01):
            eng._replication_fence(entry, False, redispatch)
        assert seen == [None]  # ambient expired deadline was shadowed
        assert bf.contains_all(keys) == len(keys)  # broadcast applied
    finally:
        client.shutdown()
