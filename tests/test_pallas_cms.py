"""Pallas heavy-hitter kernel (BASELINE config 5) — sequential CMS
update+estimate vs its NumPy twin and vs the XLA batch path.  Runs in
Pallas interpret mode on the CPU suite; the real-TPU compile is exercised
by the verify drive."""

import numpy as np
import pytest

from redisson_tpu.ops import pallas_cms


D, W = 4, 1 << 12


def rand_ops(rng, B, dup=False):
    n_keys = 50 if dup else B * 10
    h1 = (rng.integers(0, n_keys, B) * 7919 % W).astype(np.uint32)
    h2 = (rng.integers(0, n_keys, B) * 104729 % W).astype(np.uint32)
    wt = rng.integers(0, 5, B).astype(np.uint32)
    return h1, h2, wt


class TestPallasCms:
    def test_matches_sequential_golden(self):
        rng = np.random.default_rng(0)
        table = np.zeros((D, W), np.uint32)
        h1, h2, wt = rand_ops(rng, 512, dup=True)
        g_table, g_est = pallas_cms.golden_seq(table, h1, h2, wt, d=D, w=W)
        import jax.numpy as jnp

        k_table, k_est = pallas_cms.cms_update_estimate_seq(
            jnp.asarray(table), jnp.asarray(h1), jnp.asarray(h2),
            jnp.asarray(wt), d=D, w=W, interpret=True,
        )
        assert np.array_equal(np.asarray(k_table), g_table)
        assert np.array_equal(np.asarray(k_est), g_est)

    def test_no_duplicates_matches_xla_batch_path(self):
        """Without same-batch duplicates the sequential and batch
        semantics coincide — the kernel must agree with ops/cms.py."""
        import jax.numpy as jnp

        from redisson_tpu.ops import cms as cms_ops

        rng = np.random.default_rng(1)
        B = 256
        h1 = rng.permutation(W)[:B].astype(np.uint32)  # distinct cells
        h2 = np.full(B, 1, np.uint32)
        wt = rng.integers(1, 5, B).astype(np.uint32)
        _, seq_est = pallas_cms.cms_update_estimate_seq(
            jnp.zeros((D, W), jnp.uint32), jnp.asarray(h1), jnp.asarray(h2),
            jnp.asarray(wt), d=D, w=W, interpret=True,
        )
        cells = D * W
        flat = jnp.zeros((cells + 1,), jnp.uint32)
        rows = jnp.zeros(B, jnp.int32)
        _, xla_est = cms_ops.cms_update_and_estimate(
            flat, rows, jnp.asarray(h1), jnp.asarray(h2), jnp.asarray(wt),
            d=D, w=W, cells_per_row=cells,
        )
        assert np.array_equal(np.asarray(seq_est), np.asarray(xla_est)[:B])

    def test_sequential_estimates_are_monotone_upper_bounds(self):
        """Duplicates: each op's estimate >= its true running count and
        <= the XLA batch-final estimate."""
        import jax.numpy as jnp

        rng = np.random.default_rng(2)
        table = np.zeros((D, W), np.uint32)
        h1 = np.full(300, 17, np.uint32)  # one hot key, 300 adds
        h2 = np.full(300, 23, np.uint32)
        wt = np.ones(300, np.uint32)
        _, est = pallas_cms.cms_update_estimate_seq(
            jnp.asarray(table), jnp.asarray(h1), jnp.asarray(h2),
            jnp.asarray(wt), d=D, w=W, interpret=True,
        )
        est = np.asarray(est)
        assert np.array_equal(est, np.arange(1, 301, dtype=np.uint32))

    def test_zero_weight_is_pure_estimate(self):
        import jax.numpy as jnp

        table = np.zeros((D, W), np.uint32)
        h1 = np.asarray([5, 5], np.uint32)
        h2 = np.asarray([9, 9], np.uint32)
        wt = np.asarray([7, 0], np.uint32)
        new, est = pallas_cms.cms_update_estimate_seq(
            jnp.asarray(table), jnp.asarray(h1), jnp.asarray(h2),
            jnp.asarray(wt), d=D, w=W, interpret=True,
        )
        assert list(np.asarray(est)) == [7, 7]  # estimate sees the add
        assert int(np.asarray(new).sum()) == 7 * D


class TestPublicApiSeq:
    @pytest.fixture(params=["tpu", "host"])
    def client(self, request):
        import redisson_tpu
        from redisson_tpu import Config

        cfg = Config()
        if request.param == "tpu":
            cfg = cfg.use_tpu_sketch(min_bucket=64)
        c = redisson_tpu.create(cfg)
        yield c
        c.shutdown()

    def test_streaming_estimates_through_public_api(self, client):
        cms = client.get_count_min_sketch("seq")
        cms.try_init(4, 1 << 12)
        # 5 adds of one key: sequential estimates count up 1..5.
        res = cms.add_all_seq(["hot"] * 5)
        assert list(res) == [1, 2, 3, 4, 5]
        # Vectorized path on the same key sees the whole batch at once.
        res2 = cms.add_all(["hot"] * 3)
        assert list(res2) == [8, 8, 8]
        assert cms.estimate("hot") == 8

    def test_seq_matches_vectorized_table(self, client):
        import numpy as np

        a = client.get_count_min_sketch("seq-a")
        b = client.get_count_min_sketch("seq-b")
        a.try_init(4, 1 << 12)
        b.try_init(4, 1 << 12)
        rng = np.random.default_rng(0)
        keys = (rng.zipf(1.4, 3000) % 100).astype(np.uint64)
        a.add_all_seq(keys)
        b.add_all(keys)
        probe = np.arange(100, dtype=np.uint64)
        assert list(a.estimate_all(probe)) == list(b.estimate_all(probe))

    def test_seq_feeds_shared_topk(self, client):
        cms = client.get_count_min_sketch("seq-topk")
        cms.try_init(4, 1 << 12, track_top_k=2)
        cms.add_all_seq(["x"] * 30 + ["y"] * 10)
        top = cms.top_k(2)
        assert [k for k, _ in top] == ["x", "y"]

    def test_sharded_mode_falls_back(self):
        import numpy as np

        import redisson_tpu
        from redisson_tpu import Config

        c = redisson_tpu.create(
            Config().use_tpu_sketch(num_shards=8, min_bucket=64)
        )
        try:
            cms = c.get_count_min_sketch("seq-sh")
            cms.try_init(4, 1 << 12)
            res = cms.add_all_seq(np.asarray([7, 7, 7], np.uint64))
            # Fallback = vectorized semantics (whole batch visible).
            assert list(res) == [3, 3, 3]
        finally:
            c.shutdown()

    def test_odd_geometry_falls_back(self, client):
        """d*w not a 128-multiple (any try_init_by_error sizing): seq adds
        fall back to the vectorized path instead of raising."""
        cms = client.get_count_min_sketch("seq-odd")
        cms.try_init_by_error(0.001, 0.99)  # w=2719: not 128-aligned
        res = cms.add_all_seq(["k", "k"])
        # TPU engine: vectorized fallback ([2, 2]); host engine supports
        # sequential for ANY geometry ([1, 2]).  Both leave count == 2.
        assert list(res) in ([2, 2], [1, 2])
        assert cms.estimate("k") == 2
        assert cms.add_all_seq([]).tolist() == []

    def test_set_input_works(self, client):
        cms = client.get_count_min_sketch("seq-set")
        cms.try_init(4, 1 << 12, track_top_k=2)
        res = cms.add_all_seq({"x", "y"})
        assert sorted(res) == [1, 1]
