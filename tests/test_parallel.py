"""Sharded kernels on the 8-virtual-device CPU mesh vs golden models."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from redisson_tpu.ops import golden
from redisson_tpu.parallel import mesh as pm
from redisson_tpu.parallel.mesh import MeshContext
from redisson_tpu.utils import hashing


@pytest.fixture(scope="module")
def ctx():
    assert len(jax.devices()) >= 8, "conftest must force 8 cpu devices"
    return MeshContext(n_shards=8)


def _hashes(n, seed, m=None):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 1 << 63, size=n, dtype=np.uint64)
    blocks, lengths = hashing.encode_uint64_batch(keys)
    if m is None:
        return hashing.murmur3_x86_128(blocks, lengths)
    h1, h2 = hashing.hash128_np(blocks, lengths)
    return hashing.km_reduce_mod(h1, h2, m)


def test_sharded_bloom_vs_golden(ctx):
    M, K, W = 1 << 14, 5, (1 << 14) // 32
    T = 16  # 2 tenants per shard
    state = ctx.make_state(T // ctx.n_shards * W + 1, jnp.uint32)
    add = pm.sharded_bloom_add(ctx, k=K, words_per_row=W)
    query = pm.sharded_bloom_contains(ctx, k=K, words_per_row=W)
    g = [golden.GoldenBloomFilter(M, K) for _ in range(T)]
    rng = np.random.default_rng(1)
    n = 512
    h1m, h2m = _hashes(n, 2, m=M)
    rows = rng.integers(0, T, size=n).astype(np.int32)
    m_arr = np.full(n, M, np.uint32)
    valid = np.ones(n, bool)
    state, newly = add(state, rows, h1m, h2m, m_arr, valid)
    newly_g = np.zeros(n, bool)
    for t in range(T):
        sel = rows == t
        newly_g[sel] = g[t].add_hashed(h1m[sel], h2m[sel])
    np.testing.assert_array_equal(np.asarray(newly), newly_g)
    got = query(state, rows, h1m, h2m, m_arr, valid)
    assert np.asarray(got).all()
    # fresh keys mostly absent
    q1, q2 = _hashes(n, 3, m=M)
    got2 = np.asarray(query(state, rows, q1, q2, m_arr, valid))
    exp2 = np.zeros(n, bool)
    for t in range(T):
        sel = rows == t
        exp2[sel] = g[t].contains_hashed(q1[sel], q2[sel])
    np.testing.assert_array_equal(got2, exp2)
    # shard-local state equals golden rows (round-robin placement)
    host = np.asarray(state)  # [S, local]
    for t in range(T):
        shard, lrow = t % ctx.n_shards, t // ctx.n_shards
        words = host[shard][lrow * W : (lrow + 1) * W]
        bits = np.unpackbits(words.view(np.uint8), bitorder="little").astype(bool)
        np.testing.assert_array_equal(bits, g[t].bits)


def test_sharded_hll_add_hist_merge(ctx):
    M = golden.HLL_M
    T = 8
    state = ctx.make_state(T // ctx.n_shards * M + 1, jnp.uint8)
    addf = pm.sharded_hll_add(ctx)
    histf = pm.sharded_hll_histogram(ctx)
    mergef = pm.sharded_hll_merge(ctx)
    g = [golden.GoldenHyperLogLog() for _ in range(T)]
    rng = np.random.default_rng(7)
    n = 4096
    c0, c1, c2, _ = _hashes(n, 11)
    rows = rng.integers(0, T, size=n).astype(np.int32)
    valid = np.ones(n, bool)
    state = addf(state, rows, c0, c1, c2, valid)
    for t in range(T):
        sel = rows == t
        g[t].add_hashed(c0[sel], c1[sel], c2[sel])
    for t in range(T):
        hist = np.asarray(histf(state, t))
        est = golden.ertl_estimate(hist)
        assert int(round(est)) == g[t].count()
    # merge rows 1..3 (on shards 1..3) into row 0 (shard 0)
    state = mergef(state, 0, np.array([1, 2, 3], np.int32))
    g[0].merge(g[1], g[2], g[3])
    hist = np.asarray(histf(state, 0))
    assert int(round(golden.ertl_estimate(hist))) == g[0].count()


def test_sharded_mbit_giant_bitmap(ctx):
    total_bits = 1 << 18  # giant-bitmap path, small for test speed
    W_local = total_bits // 32 // ctx.n_shards
    state = ctx.make_state(W_local + 1, jnp.uint32)
    setf = pm.sharded_mbit_set(ctx, words_local=W_local)
    getf = pm.sharded_mbit_get(ctx, words_local=W_local)
    rng = np.random.default_rng(13)
    idx = rng.integers(0, total_bits, size=1024).astype(np.uint32)
    valid = np.ones(1024, bool)
    gold = golden.GoldenBitSet(total_bits)
    state, prev = setf(state, idx, valid)
    prev_g = gold.set(idx)
    np.testing.assert_array_equal(np.asarray(prev), prev_g)
    qidx = rng.integers(0, total_bits, size=2048).astype(np.uint32)
    got = np.asarray(getf(state, qidx))
    np.testing.assert_array_equal(got, gold.get(qidx))


def test_sharded_bitop(ctx):
    W = 64
    T = 8
    state = ctx.make_state(T // ctx.n_shards * W + 1, jnp.uint32)
    setf = pm.sharded_bloom_add(ctx, k=1, words_per_row=W)  # reuse as bit setter
    # use bloom_add with k=1 to set one bit per op: h1m = bit index
    rows = np.array([1, 1, 2, 2, 2], np.int32)
    bits = np.array([3, 40, 40, 50, 60], np.uint32)
    m_arr = np.full(5, W * 32, np.uint32)
    state, _ = setf(state, rows, bits, np.zeros(5, np.uint32), m_arr, np.ones(5, bool))
    opf = pm.sharded_bitop(ctx, words_per_row=W, op="or", n_src=2)
    state = opf(state, 0, np.array([1, 2], np.int32), np.int64(0))
    host = np.asarray(state)
    # row 0 lives on shard 0, local row 0
    words = host[0][:W]
    got = np.unpackbits(words.view(np.uint8), bitorder="little")
    assert sorted(np.nonzero(got)[0].tolist()) == [3, 40, 50, 60]
    opf_and = pm.sharded_bitop(ctx, words_per_row=W, op="and", n_src=2)
    state = opf_and(state, 0, np.array([1, 2], np.int32), np.int64(0))
    host = np.asarray(state)
    got = np.unpackbits(host[0][:W].view(np.uint8), bitorder="little")
    assert sorted(np.nonzero(got)[0].tolist()) == [40]
