"""Reactive (asyncio) facade — the RedissonReactiveClient/RxClient
analog (SURVEY §2.3 facades row): reflective wrapping, awaitable
methods, off-event-loop execution."""

import asyncio
import threading

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config


@pytest.fixture(params=["tpu", "host"])
def client(request):
    cfg = Config()
    if request.param == "tpu":
        cfg.use_tpu_sketch(min_bucket=64)
    c = redisson_tpu.create(cfg)
    yield c
    c.shutdown()


def test_reactive_bloom_roundtrip(client):
    rc = client.reactive()

    async def main():
        bf = rc.get_bloom_filter("rx-bf")
        assert await bf.try_init(10_000, 0.01) is True
        assert await bf.add("alice") is True
        assert await bf.contains("alice") is True
        assert await bf.contains("ghost") is False
        added = await bf.add_all(np.arange(100, dtype=np.uint64))
        assert added == 100
        return await bf.count()

    est = asyncio.run(main())
    assert est > 50


def test_reactive_grid_objects_and_camelcase(client):
    rc = client.rx()  # the RxClient spelling

    async def main():
        m = rc.get_map("rx-m")
        await m.put("k", 1)
        assert await m.get("k") == 1
        assert await m.containsKey("k") is True  # camelCase rides through
        q = rc.get_queue("rx-q")
        await q.offer("x")
        assert await q.poll() == "x"
        b = rc.getBucket("rx-b")  # camelCase factory
        await b.set("v")
        return await b.get()

    assert asyncio.run(main()) == "v"


def test_reactive_runs_off_event_loop(client):
    """Blocking work must not run on the loop thread."""
    rc = client.reactive()
    loop_thread = []

    async def main():
        loop_thread.append(threading.current_thread().name)
        q = rc.get_blocking_queue("rx-bq")

        async def producer():
            await asyncio.sleep(0.2)
            await q.offer("late")

        # A blocking poll awaited CONCURRENTLY with the producer on one
        # event loop: only possible if the poll runs off-loop.
        got, _ = await asyncio.gather(q.poll(5.0), producer())
        return got

    assert asyncio.run(main()) == "late"


def test_reactive_many_blocking_ops_no_pool_deadlock(client):
    """More concurrent blocking awaits than any bounded pool has workers
    — per-call threads mean the unblocking offer always runs."""
    rc = client.reactive()

    async def main():
        q = rc.get_blocking_queue("rx-dl")
        n = 40  # far beyond the default-executor worker count

        async def producer():
            await asyncio.sleep(0.2)
            for i in range(n):
                await q.offer(i)

        results = await asyncio.gather(
            *[q.poll(10.0) for _ in range(n)], producer()
        )
        return sorted(r for r in results[:n])

    assert asyncio.run(main()) == list(range(40))


def test_reactive_async_named_methods_resolve_to_values(client):
    """Awaiting fooAsync/*_async must yield the VALUE, not a future."""
    rc = client.reactive()

    async def main():
        m = rc.get_map("rx-av")
        await m.put("k", 7)
        got = await m.get_async("k")
        got2 = await m.getAsync("k")
        return got, got2

    assert asyncio.run(main()) == (7, 7)


def test_reactive_concurrent_awaitables(client):
    rc = client.reactive()

    async def main():
        counter = rc.get_atomic_long("rx-ctr")
        await asyncio.gather(*[counter.increment_and_get() for _ in range(50)])
        return await counter.get()

    assert asyncio.run(main()) == 50


def test_reactive_bounded_pool_for_nonblocking_ops(client):
    """Round-5 VERDICT item 6: 5k concurrent awaits of map gets must NOT
    spawn 5k threads — non-blocking methods share one bounded pool.

    Counts only the shared pool's own threads (the
    ``rtpu-async-pool`` name prefix, grid/base.py _get_shared_pool) —
    a process-wide ``threading.active_count()`` bound made the test
    order-dependent: unrelated suites leave daemon threads (RESP
    conns, coalescers, pre-warmers) alive and the global count drifts."""
    import threading

    rc = client.reactive()

    def pool_threads() -> int:
        return sum(
            1
            for t in threading.enumerate()
            if t.name.startswith("rtpu-async-pool")
        )

    async def main():
        m = rc.get_map("rx-pool")
        await m.put("k", 1)
        peak = [0]

        async def one(i):
            v = await m.get("k")
            peak[0] = max(peak[0], pool_threads())
            return v

        results = await asyncio.gather(*[one(i) for i in range(5000)])
        return results, peak[0]

    results, peak_threads = asyncio.run(main())
    assert results == [1] * 5000
    # The shared pool is bounded at min(32, cpus + 4) workers.
    assert peak_threads <= 36, peak_threads


def test_blocking_ops_still_cannot_starve_each_other(client):
    """take (blocking) held across the pool must not prevent the put
    that releases it — blocking names run on dedicated threads."""

    async def main():
        rc = client.reactive()
        q = rc.get_blocking_queue("rx-starve")
        takers = [asyncio.ensure_future(q.take()) for _ in range(64)]
        await asyncio.sleep(0.2)  # all 64 parked
        for i in range(64):
            await q.put(i)
        return sorted(await asyncio.gather(*takers))

    assert asyncio.run(main()) == list(range(64))
