"""Reactor front door (ISSUE 11).

The tentpole contract: replacing thread-per-connection serving with the
epoll reactor pool must be INVISIBLE on the wire — every connection's
reply stream is byte-identical to the thread path's, whatever the tick
boundaries, the cross-connection fusion, or the worker handoffs — while
the serving thread count stays FIXED as connections scale.  The
randomized multi-connection differential soak enforces the first half;
the thread-census tests the second.
"""

import random
import socket
import threading
import time

import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.serve.resp import RespServer
from redisson_tpu.serve.wireutil import (
    skip_reply_frame as _skip_frame,
    wire_command as _wire,
)


def _mk_server(reactor: bool, retry_attempts=None, max_connections=256,
               idle_timeout_s=300.0, **tpu_kw):
    cfg = Config().use_tpu_sketch(min_bucket=64, **tpu_kw)
    cfg.resp_reactor = reactor
    if retry_attempts is not None:
        cfg.retry_attempts = retry_attempts
    client = redisson_tpu.create(cfg)
    server = RespServer(
        client, max_connections=max_connections,
        idle_timeout_s=idle_timeout_s,
    )
    return client, server


def _recv_replies(sock, n, timeout=60.0):
    sock.settimeout(timeout)
    data = b""
    frames = []
    pos = 0
    deadline = time.monotonic() + timeout
    while len(frames) < n:
        try:
            while len(frames) < n:
                end = _skip_frame(data, pos)
                frames.append(data[pos:end])
                pos = end
        except (IndexError, ValueError):
            pass
        if len(frames) >= n:
            break
        if time.monotonic() > deadline:
            raise AssertionError(f"timeout with {len(frames)}/{n} replies")
        chunk = sock.recv(1 << 16)
        if not chunk:
            raise AssertionError(
                f"connection closed with {len(frames)}/{n} replies"
            )
        data += chunk
    return frames, data[pos:]


def _roundtrip(server, cmds, sock=None):
    own = sock is None
    if own:
        sock = socket.create_connection((server.host, server.port))
    try:
        sock.sendall(b"".join(_wire(c) for c in cmds))
        frames, rest = _recv_replies(sock, len(cmds))
        assert rest == b""
        return frames
    finally:
        if own:
            sock.close()


def _serving_threads():
    """Names of live RESP serving threads (reactors, per-conn readers,
    detach workers) — the census the fixed-thread-count contract is
    about."""
    return [
        t.name for t in threading.enumerate()
        if t.name.startswith("rtpu-resp")
    ]


@pytest.fixture(scope="module")
def rx():
    client, server = _mk_server(True)
    yield client, server
    server.close()
    client.shutdown()


class TestReactorBasics:
    def test_reactor_active_by_default(self, rx):
        client, server = rx
        assert server.reactor is not None
        assert server.reactor.nthreads == client.config.resp_reactor_threads
        frames = _roundtrip(server, [[b"PING"], [b"SET", b"rxk", b"v"],
                                     [b"GET", b"rxk"]])
        assert frames == [b"+PONG\r\n", b"+OK\r\n", b"$1\r\nv\r\n"]

    def test_fixed_thread_count_many_idle_connections(self, rx):
        _client, server = rx
        before = _serving_threads()
        socks = [
            socket.create_connection((server.host, server.port))
            for _ in range(30)
        ]
        try:
            # Every connection answers (they are live, not just queued).
            for s in socks[::7]:
                assert _roundtrip(server, [[b"PING"]], sock=s) == [
                    b"+PONG\r\n"
                ]
            after = _serving_threads()
            # No per-connection serving threads appeared: 30 idle conns
            # ride the same fixed reactor pool.
            assert not any(n == "rtpu-resp-conn" for n in after)
            assert len(after) <= len(before) + 1  # tolerate a worker blip
        finally:
            for s in socks:
                s.close()

    def test_blocking_command_does_not_stall_other_connections(self, rx):
        _client, server = rx
        blocker = socket.create_connection((server.host, server.port))
        other = socket.create_connection((server.host, server.port))
        try:
            blocker.sendall(_wire([b"BLPOP", b"rx-q", b"5"]))
            time.sleep(0.1)  # blocker is parked on a worker
            t0 = time.monotonic()
            assert _roundtrip(server, [[b"PING"]], sock=other) == [
                b"+PONG\r\n"
            ]
            assert time.monotonic() - t0 < 2.0, "reactor stalled by BLPOP"
            _roundtrip(server, [[b"LPUSH", b"rx-q", b"v"]], sock=other)
            frames, _ = _recv_replies(blocker, 1)
            assert frames[0] == b"*2\r\n$4\r\nrx-q\r\n$1\r\nv\r\n"
        finally:
            blocker.close()
            other.close()

    def test_pubsub_across_reactor_connections(self, rx):
        _client, server = rx
        sub = socket.create_connection((server.host, server.port))
        pub = socket.create_connection((server.host, server.port))
        try:
            sub.sendall(_wire([b"SUBSCRIBE", b"rx-chan"]))
            frames, _ = _recv_replies(sub, 1)
            assert b"subscribe" in frames[0]
            _roundtrip(server, [[b"PUBLISH", b"rx-chan", b"hello"]],
                       sock=pub)
            frames, _ = _recv_replies(sub, 1)
            assert frames[0] == (
                b"*3\r\n$7\r\nmessage\r\n$7\r\nrx-chan\r\n$5\r\nhello\r\n"
            )
        finally:
            sub.close()
            pub.close()

    def test_large_reply_requeue_path(self, rx):
        _client, server = rx
        big = b"x" * (300 << 10)
        frames = _roundtrip(
            server, [[b"SET", b"rx-big", big]] + [[b"GET", b"rx-big"]] * 8
        )
        want = b"$%d\r\n%s\r\n" % (len(big), big)
        assert frames[0] == b"+OK\r\n" and all(
            f == want for f in frames[1:]
        )

    def test_protocol_error_replies_then_closes(self, rx):
        _client, server = rx
        s = socket.create_connection((server.host, server.port))
        try:
            s.sendall(b"*-3\r\n")
            s.settimeout(5)
            data = s.recv(4096)
            assert data.startswith(b"-ERR Protocol error")
            assert s.recv(4096) == b""  # server closed the stream
        finally:
            s.close()

    def test_multi_exec_on_reactor(self, rx):
        _client, server = rx
        frames = _roundtrip(server, [
            [b"MULTI"], [b"SET", b"rx-m", b"1"], [b"GET", b"rx-m"],
            [b"EXEC"], [b"GET", b"rx-m"],
        ])
        assert frames[0] == b"+OK\r\n"
        assert frames[1] == frames[2] == b"+QUEUED\r\n"
        assert frames[3] == b"*2\r\n+OK\r\n$1\r\n1\r\n"
        assert frames[4] == b"$1\r\n1\r\n"


class TestConnLimitObservability:
    def test_conn_limit_refusal_counted(self):
        client, server = _mk_server(True, max_connections=2)
        try:
            keep = [
                socket.create_connection((server.host, server.port))
                for _ in range(2)
            ]
            for s in keep:
                assert _roundtrip(server, [[b"PING"]], sock=s)
            over = socket.create_connection((server.host, server.port))
            over.settimeout(5)
            assert over.recv(4096).startswith(
                b"-ERR max number of clients"
            )
            over.close()
            deadline = time.monotonic() + 5
            while server._conns_refused == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert server._conns_refused == 1
            shed = {
                lv[0]: int(c.value)
                for lv, c in server.obs.resp_ingress_shed.items()
            }
            assert shed.get("conn_limit") == 1
            info = _roundtrip(server, [[b"INFO", b"clients"]],
                              sock=keep[0])[0]
            assert b"rejected_connections:1" in info
            for s in keep:
                s.close()
        finally:
            server.close()
            client.shutdown()


class TestCmsQueryFusion:
    def test_cms_run_fuses_and_matches_sequential(self):
        client, server = _mk_server(True)
        ref_client, ref_server = _mk_server(False)
        ref_server.vectorize = False
        try:
            seed = [[b"CMS.INITBYDIM", b"rx-cms", b"512", b"4"]]
            seed += [
                [b"CMS.INCRBY", b"rx-cms", b"it%d" % i, b"%d" % (i + 1)]
                for i in range(10)
            ]
            queries = [
                [b"CMS.QUERY", b"rx-cms", b"it1", b"it2"],
                [b"CMS.QUERY", b"rx-cms", b"it3"],
                [b"CMS.QUERY", b"rx-cms", b"it9", b"missing", b"it0"],
                [b"CMS.QUERY", b"rx-cms", b"it1", b"it2"],  # cache hit
            ]
            got = _roundtrip(server, seed + queries)[len(seed):]
            want = _roundtrip(ref_server, seed + queries)[len(seed):]
            assert got == want
            assert got[0] == b"*2\r\n:2\r\n:3\r\n"
            fused = {
                lv[0]: int(c.value)
                for lv, c in server.obs.resp_fused_runs.items()
            }
            assert fused.get("cms", 0) >= 1
        finally:
            server.close()
            client.shutdown()
            ref_server.close()
            ref_client.shutdown()

    def test_uninitialized_cms_errors_per_command(self):
        client, server = _mk_server(True)
        try:
            frames = _roundtrip(server, [
                [b"CMS.QUERY", b"rx-no-cms", b"a"],
                [b"CMS.QUERY", b"rx-no-cms", b"b", b"c"],
            ])
            assert all(f.startswith(b"-") for f in frames)
            assert len(set(frames)) == 1
        finally:
            server.close()
            client.shutdown()


class TestCrossConnFusion:
    def test_merged_window_counts_cross_conn_ops(self):
        """Deterministic unit check of the merged pass: items from two
        connections fuse into one run and the cross-conn counter sees
        their ops."""
        client, server = _mk_server(True)
        try:
            _roundtrip(server, [[b"BF.RESERVE", b"xf", b"0.01", b"1000"],
                                [b"BF.ADD", b"xf", b"a"]])
            from redisson_tpu.serve.resp import _ConnCtx

            # Unconnected sockets: the merged pass never writes to them
            # (frames come back to the caller), and _ConnCtx tolerates
            # a peerless socket (addr stays "").
            a_srv, b_srv = socket.socket(), socket.socket()
            ctx_a = _ConnCtx(a_srv, server=server)
            ctx_b = _ConnCtx(b_srv, server=server)

            def tot():
                return sum(
                    int(c.value)
                    for _, c in server.obs.cross_conn_fused_ops.items()
                )

            before = tot()
            frames, consumed = server._dispatch_merged(
                [[b"BF.EXISTS", b"xf", b"a"], [b"BF.EXISTS", b"xf", b"zz"]],
                [ctx_a, ctx_b],
            )
            assert consumed == 2
            assert frames == [b":1\r\n", b":0\r\n"]
            assert tot() - before == 2
            a_srv.close()
            b_srv.close()
        finally:
            server.close()
            client.shutdown()

    def test_multi_connection_barrier_not_fused(self):
        """A connection mid-MULTI contributes no items to a fused run —
        its command must QUEUE, not execute."""
        client, server = _mk_server(True)
        try:
            _roundtrip(server, [[b"BF.RESERVE", b"xm", b"0.01", b"1000"]])
            from redisson_tpu.serve.resp import _ConnCtx

            a_srv = socket.socket()
            ctx_a = _ConnCtx(a_srv, server=server)
            ctx_m = _ConnCtx(a_srv, server=server)
            ctx_m.in_multi = True
            ctx_m.queued = []
            frames, consumed = server._dispatch_merged(
                [[b"BF.EXISTS", b"xm", b"q"], [b"BF.EXISTS", b"xm", b"q"]],
                [ctx_a, ctx_m],
            )
            assert consumed == 2
            assert frames[0] == b":0\r\n"
            assert frames[1] == b"+QUEUED\r\n"
            assert ctx_m.queued == [[b"BF.EXISTS", b"xm", b"q"]]
            a_srv.close()
        finally:
            server.close()
            client.shutdown()


class TestSlowClient:
    def test_stalled_reader_does_not_block_other_ticks(self):
        client, server = _mk_server(True)
        try:
            big = b"y" * (2 << 20)
            _roundtrip(server, [[b"SET", b"rx-slow-big", big]])
            lazy = socket.create_connection((server.host, server.port))
            lazy.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 16384)
            # Ask for the huge value and never read a byte.
            lazy.sendall(_wire([b"GET", b"rx-slow-big"]))
            time.sleep(0.3)
            # Other connections keep ticking under bounded latency.
            for _ in range(5):
                t0 = time.monotonic()
                assert _roundtrip(server, [[b"PING"]]) == [b"+PONG\r\n"]
                assert time.monotonic() - t0 < 2.0
            lazy.close()
        finally:
            server.close()
            client.shutdown()


# -- randomized multi-connection differential soak ---------------------------

_N_CONNS = 6
_N_CMDS = 120


def _seed_cmds():
    """Shared read-only fixtures every soak connection probes: reads on
    them are deterministic AND fuse across connections."""
    cmds = [[b"BF.RESERVE", b"sh-bf", b"0.01", b"4000"]]
    cmds += [[b"BF.ADD", b"sh-bf", b"it%d" % i] for i in range(0, 40, 2)]
    cmds += [[b"SETBIT", b"sh-bs", b"%d" % i, b"1"] for i in range(0, 64, 3)]
    cmds += [[b"SET", b"sh-s%d" % i, b"val-%d" % i] for i in range(4)]
    cmds += [[b"CMS.INITBYDIM", b"sh-cms", b"512", b"4"]]
    cmds += [
        [b"CMS.INCRBY", b"sh-cms", b"it%d" % i, b"%d" % (i + 1)]
        for i in range(16)
    ]
    cmds += [[b"PFADD", b"sh-h"] + [b"e%d" % i for i in range(32)]]
    return cmds


def _conn_stream(conn_id: int, rng: random.Random, n: int):
    """Deterministic per-connection command stream: reads hit the SHARED
    immutable fixtures (cross-connection fusion), writes stay on keys
    PRIVATE to this connection (so each connection's replies are
    deterministic under any interleaving)."""
    p = b"c%d" % conn_id
    cmds = [[b"BF.RESERVE", p + b"-bf", b"0.01", b"2000"],
            [b"LPUSH", p + b"-q", b"seed"]]
    it = lambda: b"it%d" % rng.randrange(40)  # noqa: E731

    def one():
        r = rng.random()
        if r < 0.28:  # shared bloom reads
            if rng.random() < 0.75:
                return [b"BF.EXISTS", b"sh-bf", it()]
            return [b"BF.MEXISTS", b"sh-bf"] + [
                it() for _ in range(rng.randrange(1, 4))
            ]
        if r < 0.42:  # shared bitset / string / cms / hll reads
            k = rng.random()
            if k < 0.3:
                return [b"GETBIT", b"sh-bs", b"%d" % rng.randrange(64)]
            if k < 0.6:
                return [b"GET", b"sh-s%d" % rng.randrange(4)]
            if k < 0.85:
                return [b"CMS.QUERY", b"sh-cms"] + [
                    it() for _ in range(rng.randrange(1, 4))
                ]
            return [b"PFCOUNT", b"sh-h"]
        if r < 0.60:  # private bloom writes/reads
            if rng.random() < 0.5:
                return [b"BF.ADD", p + b"-bf", it()]
            return [b"BF.EXISTS", p + b"-bf", it()]
        if r < 0.72:  # private bitset
            off = b"%d" % rng.randrange(128)
            if rng.random() < 0.5:
                return [b"SETBIT", p + b"-bs", off,
                        b"1" if rng.random() < 0.8 else b"0"]
            return [b"GETBIT", p + b"-bs", off]
        if r < 0.84:  # private strings
            k = p + b"-s%d" % rng.randrange(3)
            q = rng.random()
            if q < 0.4:
                return [b"SET", k, b"v%d" % rng.randrange(100)]
            if q < 0.9:
                return [b"GET", k]
            return [b"APPEND", k, b"x"]
        if r < 0.90:  # worker-handoff coverage: non-empty blocking pop
            return [b"RPOPLPUSH", p + b"-q", p + b"-q"]
        if r < 0.94:
            return [b"BLPOP", p + b"-q", b"1"]
        if r < 0.97:  # structural barrier on private keys
            return [b"DEL", p + b"-s%d" % rng.randrange(3)]
        return [b"STRLEN", p + b"-s0"]

    cmds += [one() for _ in range(n)]
    # BLPOP consumes the queue seed: re-prime so later BLPOPs stay
    # deterministic (the RPOPLPUSH rotation keeps length constant).
    fixed = []
    for c in cmds:
        fixed.append(c)
        if c[0] == b"BLPOP":
            fixed.append([b"LPUSH", p + b"-q", b"seed"])
    return fixed


def _run_soak(server, streams):
    """Each stream rides its own connection UNPIPELINED (one command in
    flight at a time — the client shape the reactor exists for);
    returns the concatenated reply bytes per connection."""
    results = [None] * len(streams)
    errors = []

    def worker(idx):
        try:
            sock = socket.create_connection((server.host, server.port))
            sock.settimeout(60)
            out = []
            for cmd in streams[idx]:
                sock.sendall(_wire(cmd))
                frames, rest = _recv_replies(sock, 1)
                assert rest == b""
                out.append(frames[0])
            results[idx] = b"".join(out)
            sock.close()
        except Exception as e:  # pragma: no cover - failure surface
            errors.append((idx, e))

    threads = [
        threading.Thread(target=worker, args=(i,))
        for i in range(len(streams))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise AssertionError(f"soak connection failed: {errors[0]}")
    return results


class TestMultiConnDifferentialSoak:
    def _streams(self, seed):
        return [
            _conn_stream(i, random.Random(seed * 97 + i), _N_CMDS)
            for i in range(_N_CONNS)
        ]

    def test_soak_byte_identical_per_connection(self):
        rx_c, rx_s = _mk_server(True)
        ref_c, ref_s = _mk_server(False)
        try:
            for srv in (rx_s, ref_s):
                _roundtrip(srv, _seed_cmds())
            streams = self._streams(3)
            got = _run_soak(rx_s, streams)
            want = _run_soak(ref_s, streams)
            for i in range(_N_CONNS):
                assert got[i] == want[i], (
                    f"connection {i} reply stream diverged "
                    "(reactor vs thread-per-connection)"
                )
            # The reactor arm really ran on the reactor.
            assert rx_s.reactor is not None and ref_s.reactor is None
        finally:
            rx_s.close()
            rx_c.shutdown()
            ref_s.close()
            ref_c.shutdown()

    def test_soak_byte_identical_under_chaos(self):
        """Chaos error injection at the fused dispatch points: the
        coalescer's retry discipline absorbs injected faults, so both
        serving modes still answer byte-identically per connection."""
        from redisson_tpu import chaos

        rx_c, rx_s = _mk_server(True, retry_attempts=8)
        ref_c, ref_s = _mk_server(False, retry_attempts=8)
        try:
            for srv in (rx_s, ref_s):
                _roundtrip(srv, _seed_cmds())
            for point in (
                "dispatch.bloom_mixed_keys",
                "dispatch.bloom_mixed_keys_runs",
                "dispatch.bitset_mixed",
                "dispatch.bitset_mixed_runs",
                "dispatch.cms_update_estimate",
            ):
                chaos.inject(point, kind="error", rate=0.03, seed=41)
            streams = self._streams(7)
            got = _run_soak(rx_s, streams)
            want = _run_soak(ref_s, streams)
            for i in range(_N_CONNS):
                assert got[i] == want[i], f"chaos soak diverged (conn {i})"
        finally:
            chaos.clear()
            rx_s.close()
            rx_c.shutdown()
            ref_s.close()
            ref_c.shutdown()

    def test_soak_with_stalled_reader(self):
        """A stalled reader (never reads its big reply) must not block
        the other connections' ticks — they complete their streams."""
        rx_c, rx_s = _mk_server(True)
        try:
            _roundtrip(rx_s, _seed_cmds())
            big = b"z" * (1 << 20)
            _roundtrip(rx_s, [[b"SET", b"sh-stall", big]])
            lazy = socket.create_connection((rx_s.host, rx_s.port))
            lazy.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 16384)
            lazy.sendall(_wire([b"GET", b"sh-stall"]))
            time.sleep(0.2)
            streams = self._streams(11)
            t0 = time.monotonic()
            got = _run_soak(rx_s, streams)
            assert all(r is not None for r in got)
            assert time.monotonic() - t0 < 120
            lazy.close()
        finally:
            rx_s.close()
            rx_c.shutdown()


class TestRequireReactorEnv:
    def test_require_reactor_env_guards_silent_fallback(self, monkeypatch):
        """RTPU_REQUIRE_REACTOR turns a reactor-init failure into a hard
        error (the CI analog of RTPU_REQUIRE_NATIVE_RESP) instead of a
        silent thread-per-connection fallback."""
        import redisson_tpu.serve.reactor as reactor_mod

        client = redisson_tpu.create(
            Config().use_tpu_sketch(min_bucket=64)
        )
        try:
            monkeypatch.setenv("RTPU_REQUIRE_REACTOR", "1")
            monkeypatch.setattr(
                reactor_mod.ReactorPool, "__init__",
                lambda self, *a, **k: (_ for _ in ()).throw(
                    OSError("no epoll")
                ),
            )
            with pytest.raises(OSError):
                RespServer(client)
        finally:
            client.shutdown()
