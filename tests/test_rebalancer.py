"""Autonomous rebalancer (ISSUE 19): the pure planner's damping rules
(EWMA warmup, hysteresis dead band, per-slot cooldown, the mega-slot
refusal, drain and cold-pack phases), the last-moment eligibility
predicates, the write-time slot->key index and its DEBUG-scan
differential, the CLUSTER REBALANCE / CONFIG surfaces, the heat-driven
end-to-end loop over two in-process cluster nodes, fleet_loadmap's
dead-member degradation, and (slow-marked) elastic join/drain through
the subprocess supervisor.
"""

import json
import socket
import time

import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.cluster import rebalancer as rb_mod
from redisson_tpu.cluster.rebalancer import (
    Move,
    RebalanceAgent,
    RebalancePlanner,
    blocked_reason,
    run_wave,
)
from redisson_tpu.cluster.slotindex import SlotKeyIndex
from redisson_tpu.cluster.slotmap import SlotMap
from redisson_tpu.cluster.slots import NSLOTS, key_slot
from redisson_tpu.serve.resp import RespServer
from test_resp_server import RespClient


# -- planner helpers ---------------------------------------------------------


def _feed(planner, rates, ticks, keys=None, start=0):
    """Drive ``observe`` with synthetic CUMULATIVE counters: ``rates``
    maps node -> {slot: ops_per_tick}; tick 0 establishes baselines."""
    keys = keys or {}
    for t in range(start, start + ticks):
        per_node = {
            node: {
                slot: (float(rate * t), 0.0, keys.get(slot, 1))
                for slot, rate in slots.items()
            }
            for node, slots in rates.items()
        }
        planner.observe(per_node, now=float(t))


def test_planner_first_observation_is_baseline_only():
    p = RebalancePlanner()
    p.observe({"A": {7: (1000.0, 0.0, 3)}}, now=0.0)
    # A huge first reading is a counter BASELINE, not a spike: a node
    # handed a slot (restarted counters) must never read as hot.
    assert p.heat == {}
    assert p.slot_keys[7] == 3
    p.observe({"A": {7: (1100.0, 0.0, 3)}}, now=1.0)
    assert p.heat[7] == pytest.approx(0.3 * 100.0)


def test_planner_warmup_gate_blocks_early_waves():
    p = RebalancePlanner(warmup_ticks=3)
    _feed(p, {"A": {1: 100, 2: 100}}, ticks=2)  # ticks == 2 < 3
    owners = {1: "A", 2: "A", 3: "B"}
    assert p.plan(owners, ["A", "B"]) == []
    _feed(p, {"A": {1: 100, 2: 100}}, ticks=2, start=2)
    assert p.ticks >= 3
    assert p.plan(owners, ["A", "B"]) != []


def test_planner_hot_shed_and_hysteresis_dead_band():
    p = RebalancePlanner(warmup_ticks=1)
    _feed(p, {"A": {s: 100 for s in (1, 2, 3, 4)}}, ticks=5)
    owners = {1: "A", 2: "A", 3: "A", 4: "A", 5: "B"}
    moves = p.plan(owners, ["A", "B"])
    # ratio 2.0: shed down past the half-band (1.15), which lands at a
    # perfect 1.0 split after two equal-heat slots.
    assert [m.src for m in moves] == ["A", "A"]
    assert all(m.dst == "B" for m in moves)
    assert len(moves) == 2
    # Hottest-first and recorded heat carried on the move.
    assert moves[0].heat >= moves[1].heat > 0
    # Apply the wave; at the new split the ratio is 1.0 -> quiet.
    for m in moves:
        owners[m.slot] = m.dst
    _feed(p, {"A": {s: 100 for s in (1, 2, 3, 4)}}, ticks=2, start=5)
    assert [m for m in p.plan(owners, ["A", "B"]) if m.heat > 0] == []
    assert p.last_ratio == pytest.approx(1.0, abs=0.2)


def test_planner_below_threshold_never_triggers():
    # 5 vs 4 equal slots: ratio 10/9 < 1.3 — inside the dead band,
    # chasing it would be exactly the churn the EWMA exists to stop.
    p = RebalancePlanner(warmup_ticks=1, threshold=1.3)
    rates = {"A": {s: 100 for s in range(5)},
             "B": {s: 100 for s in range(10, 14)}}
    _feed(p, rates, ticks=4)
    owners = {s: "A" for s in range(5)}
    owners.update({s: "B" for s in range(10, 14)})
    assert p.plan(owners, ["A", "B"]) == []
    assert 1.0 < p.last_ratio < 1.3


def test_planner_cooldown_blocks_ping_pong():
    p = RebalancePlanner(warmup_ticks=1, cooldown_s=10.0)
    _feed(p, {"A": {s: 100 for s in (1, 2, 3, 4)}}, ticks=4)
    owners = {1: "A", 2: "A", 3: "A", 4: "A", 5: "B"}
    first = p.plan(owners, ["A", "B"], now=100.0)
    assert first
    for m in first:
        p.note_moved(m.slot, now=100.0)
    # Inside the cooldown the SAME slots are untouchable; the remaining
    # candidates can't close the gap without overshooting, so: quiet.
    again = p.plan(owners, ["A", "B"], now=101.0)
    assert not any(
        m.slot in {f.slot for f in first} for m in again
    )
    # Cooldown expiry re-arms them.
    later = p.plan(owners, ["A", "B"], now=200.0)
    assert later


def test_planner_mega_slot_never_bounces():
    # ALL heat in one indivisible slot: moving it just swaps which node
    # is hot (h > gap/2), so the planner must refuse forever.
    p = RebalancePlanner(warmup_ticks=1)
    _feed(p, {"A": {9: 1000}}, ticks=4)
    owners = {9: "A", 10: "B"}
    assert p.plan(owners, ["A", "B"]) == []
    assert p.last_ratio == pytest.approx(2.0)


def test_planner_excluded_nodes_untouchable():
    p = RebalancePlanner(warmup_ticks=1)
    _feed(p, {"C": {s: 100 for s in (1, 2, 3, 4)}}, ticks=4)
    owners = {1: "C", 2: "C", 3: "C", 4: "C", 5: "A", 6: "B"}
    # C is the hot node but it is failover-excluded: nothing may pump
    # FROM it (its keys are unreachable) and nothing lands ON it.
    moves = p.plan(owners, ["A", "B", "C"], excluded=("C",))
    assert not any(m.src == "C" or m.dst == "C" for m in moves)


def test_planner_drain_ignores_warmup_and_empties_node():
    p = RebalancePlanner(warmup_ticks=3, max_moves=8)
    assert p.ticks == 0  # cold planner: drain is operator intent
    p.drain("B")
    owners = {1: "A", 2: "B", 3: "B", 4: "B"}
    moves = p.plan(owners, ["A", "B"])
    assert sorted(m.slot for m in moves) == [2, 3, 4]
    assert all(m.src == "B" and m.dst == "A" for m in moves)
    p.undrain("B")
    assert p.plan(owners, ["A", "B"]) == []


def test_planner_cold_pack_consolidates_idle_keyed_slots():
    p = RebalancePlanner(warmup_ticks=1, max_moves=8)
    # Balanced live heat on A and B, plus a keyed slot on B whose
    # counters never move (constant cumulative ops -> zero delta).
    rates = {"A": {1: 100}, "B": {2: 100, 77: 0}}
    _feed(p, rates, ticks=4, keys={77: 50})
    assert 77 in p.slot_keys and 77 not in p.heat
    owners = {1: "A", 2: "B", 77: "B"}
    moves = p.plan(owners, ["A", "B"])
    # Balanced (ratio 1.0): phase 3 packs the observed-idle keyed slot
    # onto the least-loaded node so tiered residency can spill it.
    assert moves == [Move(77, "B", "A", 0.0)]


def test_planner_min_heat_floor_keeps_idle_cluster_still():
    p = RebalancePlanner(warmup_ticks=1, min_heat=1.0)
    # A trickle: imbalance ratio is large but the fleet is idle.
    _feed(p, {"A": {1: 0.1}}, ticks=4)
    owners = {1: "A", 2: "B"}
    assert p.plan(owners, ["A", "B"]) == []


def test_planner_forget_node_resets_baseline():
    p = RebalancePlanner()
    _feed(p, {"A": {3: 100}}, ticks=3)
    assert ("A", 3) in p._prev
    p.forget_node("A")
    assert ("A", 3) not in p._prev
    # The restarted node's lower counter is a NEW baseline, not a
    # negative delta (max(0, ...) guards the other direction too).
    before = p.heat.get(3, 0.0)
    p.observe({"A": {3: (5.0, 0.0, 1)}}, now=10.0)
    assert p.heat.get(3, 0.0) <= before  # decayed, never spiked


# -- last-moment eligibility (the netsim guard seams) ------------------------


def _map3():
    return SlotMap.from_dict({"nodes": [
        {"id": "A", "host": "h", "port": 1, "slots": [[0, 99]]},
        {"id": "B", "host": "h", "port": 2, "slots": [[100, 199]]},
        {"id": "C", "host": "h", "port": 3, "slots": []},
    ]})


def test_blocked_reason_busy_stale_failover_precedence():
    m = _map3()
    mv = Move(5, "A", "B", 1.0)
    assert blocked_reason(m, mv) is None
    m.set_migrating(5, "B")
    assert blocked_reason(m, mv) == "busy"
    m.set_stable(5)
    m.set_owner(5, "C")
    assert blocked_reason(m, mv) == "stale"
    m.set_owner(5, "A")
    assert blocked_reason(m, mv, excluded=("B",)) == "failover"
    assert blocked_reason(m, mv, excluded=("A",)) == "failover"
    assert blocked_reason(m, mv) is None
    # IMPORTING state (the destination half of a live pump) also busies.
    m.set_importing(5, "C")
    assert blocked_reason(m, mv) == "busy"


def test_run_wave_skips_without_dialing(monkeypatch):
    # A fully-blocked wave must not open a single socket.
    def boom(*a, **k):
        raise AssertionError("run_wave dialed for a blocked move")

    monkeypatch.setattr(rb_mod._supervisor, "migrate_slot", boom)
    m = _map3()
    m.set_migrating(5, "B")
    recs = run_wave(m, [
        Move(5, "A", "B", 1.0),          # busy
        Move(150, "A", "C", 1.0),        # stale (B owns 150)
        Move(6, "A", "C", 1.0),          # failover (C excluded)
    ], excluded=("C",))
    assert [r["outcome"] for r in recs] == [
        "skip_busy", "skip_stale", "skip_failover"
    ]
    assert all(r["keys"] == 0 for r in recs)


# -- write-time slot->key index ---------------------------------------------


def test_slot_key_index_note_seed_and_buckets():
    idx = SlotKeyIndex()
    s = key_slot("k1")
    idx.note("k1", +1)
    idx.note(b"k1", +1)  # bytes and str agree on one entry
    assert idx.keys(s) == ["k1"]
    assert idx.count(s) == 1
    idx.note("k1", -1)
    assert idx.keys(s) == []
    assert idx.nonempty_slots() == []  # empty bucket deleted, not kept
    idx.note("x", -1)  # removing an unseen key is a no-op
    idx.seed(["a", b"b", "c"])
    assert sorted(
        k for sl in idx.nonempty_slots() for k in idx.keys(sl)
    ) == ["a", "b", "c"]
    # Deterministic order + count limit.
    tagged = ["{t}%d" % i for i in range(5)]
    idx.seed(tagged)
    ts = key_slot(tagged[0])
    assert idx.keys(ts) == sorted(tagged)
    assert idx.keys(ts, count=2) == sorted(tagged)[:2]
    assert idx.count(ts) == 5


# -- in-process two-node cluster (engine-backed: the index is wired) ---------


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _ClusterRB:
    """Two cluster RespServers on the TPU-path engine (jax on CPU) so
    BOTH keyspace backends hook the slot index — splitting at 8192."""

    def __init__(self):
        pa, pb = _free_port(), _free_port()
        topo = {"nodes": [
            {"id": "A", "host": "127.0.0.1", "port": pa,
             "slots": [[0, 8191]]},
            {"id": "B", "host": "127.0.0.1", "port": pb,
             "slots": [[8192, NSLOTS - 1]]},
        ]}
        self.nodes = {}
        for nid, port in (("A", pa), ("B", pb)):
            cfg = Config().use_tpu_sketch(min_bucket=64)
            cfg.cluster_enabled = True
            cfg.cluster_topology = topo
            cfg.cluster_node_id = nid
            client = redisson_tpu.create(cfg)
            self.nodes[nid] = (client, RespServer(client, port=port))
        self.addr = {"A": ("127.0.0.1", pa), "B": ("127.0.0.1", pb)}

    def server(self, nid):
        return self.nodes[nid][1]

    def conn(self, nid):
        return RespClient(*self.addr[nid])

    def keys_for(self, nid, n, distinct_slots=True, prefix="rk"):
        """n keys owned by ``nid``, optionally in n distinct slots."""
        out, slots, i = [], set(), 0
        while len(out) < n:
            k = f"{prefix}{i}"
            i += 1
            s = key_slot(k)
            owned = (s < 8192) == (nid == "A")
            if owned and (not distinct_slots or s not in slots):
                out.append(k)
                slots.add(s)
        return out

    def close(self):
        for client, server in self.nodes.values():
            server.close()
            client.shutdown()


@pytest.fixture(scope="module")
def crb():
    c = _ClusterRB()
    yield c
    c.close()


def test_slot_index_wired_and_agrees_with_debug_scan(crb):
    conn = crb.conn("A")
    try:
        door = crb.server("A").cluster
        assert door.slot_index is not None, "engine path must wire it"
        keys = crb.keys_for("A", 3, distinct_slots=False, prefix="ix")
        for k in keys:
            conn.cmd("SET", k, "v")
        for k in keys:
            s = key_slot(k)
            fast = conn.cmd("CLUSTER", "GETKEYSINSLOT", s, 100)
            slow = conn.cmd("DEBUG", "GETKEYSINSLOT", s)
            assert sorted(fast) == sorted(slow), (k, fast, slow)
            assert k.encode() in fast
            assert conn.cmd("CLUSTER", "COUNTKEYSINSLOT", s) == \
                conn.cmd("DEBUG", "COUNTKEYSINSLOT", s)
        # Deletes retract from the index too (the no-ghost contract).
        conn.cmd("DEL", keys[0])
        s0 = key_slot(keys[0])
        assert keys[0].encode() not in conn.cmd(
            "CLUSTER", "GETKEYSINSLOT", s0, 100
        )
        assert sorted(conn.cmd("CLUSTER", "GETKEYSINSLOT", s0, 100)) \
            == sorted(conn.cmd("DEBUG", "GETKEYSINSLOT", s0))
    finally:
        conn.close()


def test_cluster_rebalance_status_works_unarmed(crb):
    conn = crb.conn("A")
    try:
        st = json.loads(conn.cmd("CLUSTER", "REBALANCE", "STATUS"))
        assert st == {"enabled": False, "node": "A"}
        # Bare REBALANCE defaults to STATUS.
        st2 = json.loads(conn.cmd("CLUSTER", "REBALANCE"))
        assert st2["enabled"] is False
        # Action verbs refuse without the agent (no fake capability).
        for verb in ("PAUSE", "RESUME", "NOW", "DRAIN", "UNDRAIN"):
            with pytest.raises(RuntimeError, match="not armed"):
                conn.cmd("CLUSTER", "REBALANCE", verb, "B")
    finally:
        conn.close()


def test_cluster_meet_teaches_new_member(crb):
    conn = crb.conn("B")
    try:
        port = _free_port()
        assert conn.cmd(
            "CLUSTER", "MEET", "node-new", "127.0.0.1", port
        ) == "OK"
        assert crb.server("B").cluster.slotmap.addr("node-new") == \
            ("127.0.0.1", port)
        with pytest.raises(RuntimeError):
            conn.cmd("CLUSTER", "MEET", "node-short")
    finally:
        conn.close()


# -- the armed agent: surfaces, knobs, and a heat-driven wave ----------------


def test_agent_surfaces_config_and_heat_driven_wave():
    crb = _ClusterRB()
    conn = crb.conn("A")
    try:
        srv = crb.server("A")
        agent = RebalanceAgent(
            srv, interval_s=60.0, threshold=1.3, max_moves=8,
            pace_s=0.0, cooldown_s=0.5,
        )  # NOT thread-started: CLUSTER REBALANCE NOW drives ticks
        assert srv.rebalancer is agent

        # STATUS over RESP: armed, and A (lowest id) coordinates.
        st = json.loads(conn.cmd("CLUSTER", "REBALANCE", "STATUS"))
        assert st["enabled"] and st["node"] == "A"
        assert st["coordinator"] == "A" and st["is_coordinator"]
        assert st["interval_ms"] == 60000 and st["threshold"] == 1.3

        # PAUSE freezes the periodic loop (a paused tick is a no-op)…
        assert conn.cmd("CLUSTER", "REBALANCE", "PAUSE") == "OK"
        assert json.loads(
            conn.cmd("CLUSTER", "REBALANCE", "STATUS")
        )["paused"]
        assert agent.tick() == 0 and agent.planner.ticks == 0
        assert conn.cmd("CLUSTER", "REBALANCE", "RESUME") == "OK"

        # DRAIN/UNDRAIN mark planner intent.
        assert conn.cmd("CLUSTER", "REBALANCE", "DRAIN", "B") == "OK"
        assert json.loads(
            conn.cmd("CLUSTER", "REBALANCE", "STATUS")
        )["draining"] == ["B"]
        assert conn.cmd("CLUSTER", "REBALANCE", "UNDRAIN", "B") == "OK"
        with pytest.raises(RuntimeError, match="verb"):
            conn.cmd("CLUSTER", "REBALANCE", "BOGUS")

        # CONFIG rows registered (the agent was armed before the first
        # CONFIG call built the table) and live-apply to the planner.
        assert conn.cmd("CONFIG", "GET", "rebalance-threshold") == [
            b"rebalance-threshold", b"1.3",
        ]
        assert conn.cmd(
            "CONFIG", "SET", "rebalance-threshold", "1.5",
            "rebalance-max-moves", "4", "rebalance-pace-ms", "10",
            "rebalance-cooldown-ms", "500",
            "rebalance-interval-ms", "30000",
        ) == "OK"
        assert agent.planner.threshold == 1.5
        assert agent.planner.max_moves == 4
        assert agent.pace_s == pytest.approx(0.010)
        assert agent.planner.cooldown_s == pytest.approx(0.5)
        assert agent.interval_s == pytest.approx(30.0)
        for bad in (("rebalance-threshold", "0.5"),
                    ("rebalance-threshold", "nope"),
                    ("rebalance-max-moves", "0"),
                    ("rebalance-interval-ms", "x")):
            with pytest.raises(RuntimeError):
                conn.cmd("CONFIG", "SET", *bad)
        assert agent.planner.threshold == 1.5  # validate-all held
        conn.cmd("CONFIG", "SET", "rebalance-threshold", "1.3")

        # Heat-driven wave: 4 hot slots on A, zero on B.  NOW forces
        # synchronous ticks; the first establishes baselines, warmup
        # holds the next two, then the wave sheds toward B.
        hot = crb.keys_for("A", 4, distinct_slots=True, prefix="hot")
        executed = 0
        for _ in range(8):
            for k in hot:
                for _i in range(25):
                    conn.cmd("SET", k, "v")
            executed = conn.cmd("CLUSTER", "REBALANCE", "NOW")
            assert isinstance(executed, int)
            if executed:
                break
        assert executed > 0, "no wave after 8 forced ticks"

        # Both slot maps agree on every moved slot's new owner, and the
        # moved keys serve on B (no MOVED bounce — really migrated).
        ma = crb.server("A").cluster.slotmap
        mb = crb.server("B").cluster.slotmap
        moved_slots = [
            s for s in (key_slot(k) for k in hot)
            if ma.owner(s) == "B"
        ]
        assert moved_slots, "a wave ran but no hot slot changed owner"
        for s in moved_slots:
            assert mb.owner(s) == "B"
        connb = crb.conn("B")
        try:
            moved_keys = [
                k for k in hot if key_slot(k) in moved_slots
            ]
            for k in moved_keys:
                assert connb.cmd("GET", k) == b"v"
                # The index followed the migration on BOTH ends: B's
                # RESTOREs registered, A's pump deletes retracted —
                # cross-checked against the DEBUG ground-truth scan.
                s = key_slot(k)
                assert sorted(
                    connb.cmd("CLUSTER", "GETKEYSINSLOT", s, 100)
                ) == sorted(connb.cmd("DEBUG", "GETKEYSINSLOT", s))
                assert conn.cmd("CLUSTER", "COUNTKEYSINSLOT", s) == 0
                assert conn.cmd("DEBUG", "COUNTKEYSINSLOT", s) == 0
        finally:
            connb.close()

        # Book-keeping + telemetry: counters, histogram, the imbalance
        # gauge (wired to the planner), and STATUS totals.
        st = json.loads(conn.cmd("CLUSTER", "REBALANCE", "STATUS"))
        assert st["waves"] >= 1
        assert st["slots_moved"] >= len(moved_slots)
        assert st["keys_moved"] >= len(moved_keys)
        assert st["failures"] == 0 and st["down"] == []
        body = srv.obs.registry.render_prometheus()
        assert 'rtpu_rebalancer_decisions_total{kind="planned"}' in body
        assert 'rtpu_rebalancer_decisions_total{kind="moved"}' in body
        assert "rtpu_rebalancer_keys_moved_total" in body
        assert "rtpu_rebalancer_migration_seconds" in body
        assert "rtpu_rebalancer_imbalance_ratio" in body
    finally:
        conn.close()
        crb.close()


# -- fleet_loadmap degrades when a member dies mid-scrape --------------------


def test_fleet_loadmap_degrades_not_raises_on_dead_member():
    c2 = _make_plain_pair()
    client = None
    try:
        from redisson_tpu.cluster.client import ClusterClient

        client = ClusterClient([c2.addr["A"], c2.addr["B"]])
        ka = c2.key_for("A")
        kb = c2.key_for("B")
        client.execute(b"SET", ka.encode(), b"1")
        client.execute(b"SET", kb.encode(), b"1")
        fl = client.fleet_loadmap()
        assert fl["down_nodes"] == []
        # Node B dies; the NEXT scrape must degrade, never raise.
        cl_b, srv_b = c2.nodes.pop("B")
        srv_b.close()
        cl_b.shutdown()
        fl = client.fleet_loadmap()
        b_tag = "%s:%d" % c2.addr["B"]
        assert fl["down_nodes"] == [b_tag]
        assert "error" in fl["nodes"][b_tag]
        # The survivor's view is intact (its slots still merge).
        assert any(
            row["node"] == "%s:%d" % c2.addr["A"]
            for row in fl["slots"].values()
        )
        # Same discipline on the rebalance fan-out helpers.
        rs = client.rebalance_status()
        assert "error" in rs[b_tag]
        assert rs["%s:%d" % c2.addr["A"]]["enabled"] is False
        assert client.rebalance_pause() == 0  # nobody armed, nobody up
    finally:
        if client is not None:
            client.close()
        c2.close()


def _make_plain_pair():
    """Host-engine two-node cluster (cheap: no jax engine needed for
    the loadmap/fan-out surface)."""
    pa, pb = _free_port(), _free_port()
    topo = {"nodes": [
        {"id": "A", "host": "127.0.0.1", "port": pa,
         "slots": [[0, 8191]]},
        {"id": "B", "host": "127.0.0.1", "port": pb,
         "slots": [[8192, NSLOTS - 1]]},
    ]}

    class _Pair:
        def __init__(self):
            self.nodes = {}
            for nid, port in (("A", pa), ("B", pb)):
                cfg = Config()
                cfg.cluster_enabled = True
                cfg.cluster_topology = topo
                cfg.cluster_node_id = nid
                client = redisson_tpu.create(cfg)
                self.nodes[nid] = (client, RespServer(client, port=port))
            self.addr = {"A": ("127.0.0.1", pa), "B": ("127.0.0.1", pb)}

        def key_for(self, nid, prefix="fk"):
            i = 0
            while True:
                k = f"{prefix}{i}"
                if (key_slot(k) < 8192) == (nid == "A"):
                    return k
                i += 1

        def close(self):
            for client, server in self.nodes.values():
                server.close()
                client.shutdown()

    return _Pair()


# -- elastic join/drain end to end (subprocess fleet; CI rebalance-soak) -----


@pytest.mark.slow
def test_add_node_and_drain_node_e2e():
    """ISSUE 19 acceptance: a node joins a live 2-node fleet and takes
    an even slot share, traffic is served throughout, draining it hands
    every slot back and retires the process cleanly, and the supervisor
    roster (alive/shutdown — the CI no-orphans contract) tracks the
    added node for its whole life."""
    from redisson_tpu.cluster.supervisor import ClusterSupervisor

    sup = ClusterSupervisor(n_nodes=2).start()
    try:
        client = sup.client()
        try:
            keys = [f"jd{i}" for i in range(60)]
            for k in keys:
                assert client.execute(b"SET", k.encode(), b"v1") == b"OK"

            idx = sup.add_node()
            assert idx == 2
            assert idx in sup.alive()
            assert sup.primary_alive(idx)
            new_id = sup.node_ids[idx]
            owned = sum(
                end - start + 1
                for start, end, nid, _h, _p in sup.slots_table()
                if nid == new_id
            )
            # An even 1/3 share (the supervisor-driven shift), and the
            # whole space still covered exactly once.
            assert NSLOTS // 4 < owned < NSLOTS // 2
            assert sum(
                end - start + 1
                for start, end, _n, _h, _p in sup.slots_table()
            ) == NSLOTS

            # Zero acked-write loss across the join, and the fleet
            # serves (reads AND writes) with the newcomer in rotation.
            client.refresh_slots()
            for k in keys:
                assert client.execute(b"GET", k.encode()) == b"v1"
            for k in keys:
                assert client.execute(b"SET", k.encode(), b"v2") == b"OK"

            # Drain hands everything back and retires the process.
            assert sup.drain_node(idx) is True
            assert not any(
                nid == new_id
                for _s, _e, nid, _h, _p in sup.slots_table()
            )
            assert idx not in sup.alive()
            client.refresh_slots()
            for k in keys:
                assert client.execute(b"GET", k.encode()) == b"v2"
        finally:
            client.close()
    finally:
        assert sup.shutdown() is True  # every spawned process reaped
        assert sup.alive() == []
