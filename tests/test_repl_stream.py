"""Replication stream + replica bootstrap (ISSUE 18 tentpole).

Two in-process RespServers — a journaled primary and a replica wired
through ``start_replication_from`` — exercise the whole plane over the
real wire protocol: FULLRESYNC bootstrap, live streaming across object
kinds (sketch rows AND grid keyspace), partial resync after a link
drop, the PSYNC ladder, the WAIT replica-ack fence, INFO replication
on both ends, the -READONLY / -STALEREAD read gates, and FAILOVER
promotion.  The chaos-marked soak at the bottom is satellite 2's
convergence proof: a replica streaming through 5% drop + corrupt link
faults ends bit-identical to its primary.

(tests/test_replication.py is the OTHER replication: per-mesh-shard
read copies of one hot sketch inside a single engine.)

The multi-process story (supervisor-spawned replicas, kill -9
takeover) lives in tests/test_failover.py; the election rules proper
are modeled in tests/test_netsim_failover.py.
"""

import socket
import time

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config, chaos
from redisson_tpu.codecs import LongCodec
from redisson_tpu.serve.resp import RespServer
from redisson_tpu.serve.wireutil import ReplyError, exchange


def make_cfg(tmp_path, name, journal=True, snap=True):
    cfg = Config().set_codec(LongCodec()).use_tpu_sketch(min_bucket=64)
    if snap:
        cfg.snapshot_dir = str(tmp_path / name / "snap")
    if journal:
        cfg.journal_dir = str(tmp_path / name / "journal")
        cfg.journal_fsync = "no"
    return cfg


def engine_rows(eng):
    eng._drain()
    out = {}
    for e in eng.registry.entries():
        out[e.name] = np.asarray(
            eng.executor.read_row(e.pool, e.row)
        ).copy()
    return out


class ReplPair:
    """A journaled primary and an (optionally started) replica, both
    full RespServers on loopback, with lazily opened client sockets."""

    def __init__(self, tmp_path):
        self.tmp_path = tmp_path
        self.primary = redisson_tpu.create(make_cfg(tmp_path, "primary"))
        self.pserver = RespServer(self.primary, host="127.0.0.1", port=0)
        self.replica = None
        self.rserver = None
        self.link = None
        self._socks = []

    def start_replica(self, replid=None, snap=False):
        self.replica = redisson_tpu.create(
            make_cfg(self.tmp_path, "replica", journal=False, snap=snap)
        )
        self.rserver = RespServer(self.replica, host="127.0.0.1", port=0)
        self.link = self.rserver.start_replication_from(
            self.pserver.host, self.pserver.port, replid=replid
        )
        return self.link

    def sock(self, server):
        s = socket.create_connection((server.host, server.port), timeout=10)
        self._socks.append(s)
        return s

    def cmd(self, sock, *args):
        (reply,) = exchange(sock, [args])
        return reply

    def pcmd(self, *args):
        if not hasattr(self, "_p"):
            self._p = self.sock(self.pserver)
        return self.cmd(self._p, *args)

    def rcmd(self, *args):
        if not hasattr(self, "_r"):
            self._r = self.sock(self.rserver)
        return self.cmd(self._r, *args)

    def head(self):
        return self.primary._engine.journal.last_seq()

    def wait_caught_up(self, timeout_s=20.0):
        head = self.head()
        deadline = time.monotonic() + timeout_s
        while self.link.applied < head:
            if time.monotonic() > deadline:
                raise AssertionError(
                    f"replica stuck at {self.link.applied} < {head} "
                    f"(link_up={self.link.link_up})"
                )
            time.sleep(0.02)
        return head

    def close(self):
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass
        if self.rserver is not None:
            self.rserver.close()
        self.pserver.close()
        if self.replica is not None:
            self.replica.config.snapshot_dir = None
            self.replica._engine.config.snapshot_dir = None
            self.replica.shutdown()
        self.primary.config.snapshot_dir = None
        self.primary._engine.config.snapshot_dir = None
        self.primary.shutdown()


@pytest.fixture
def pair(tmp_path):
    chaos.clear()
    p = ReplPair(tmp_path)
    yield p
    chaos.clear()
    p.close()


def seed_primary(pair, n=40):
    """Writes spanning BOTH backends the stream must carry: sketch ops
    (BF.*) and grid-keyspace ops (HSET/SET)."""
    assert pair.pcmd("BF.RESERVE", "bf", "0.01", "1000") == b"OK"
    for i in range(n):
        pair.pcmd("BF.ADD", "bf", str(i))
    assert pair.pcmd("HSET", "h", "f1", "v1") == 1
    assert pair.pcmd("SET", "plain", "value") == b"OK"


class TestBootstrapAndStream:
    def test_fullresync_bootstrap_then_live_stream(self, pair):
        seed_primary(pair)
        link = pair.start_replica()
        pair.wait_caught_up()
        assert link.full_resyncs == 1
        assert link.link_up
        # Bootstrapped state serves on the replica across both kinds.
        assert pair.rcmd("BF.EXISTS", "bf", "5") == 1
        assert pair.rcmd("BF.EXISTS", "bf", "999") == 0
        assert pair.rcmd("HGET", "h", "f1") == b"v1"
        assert pair.rcmd("GET", "plain") == b"value"
        # Live ops stream after the bootstrap cut.
        pair.pcmd("BF.ADD", "bf", "1001")
        pair.pcmd("HSET", "h", "f2", "v2")
        pair.wait_caught_up()
        assert pair.rcmd("BF.EXISTS", "bf", "1001") == 1
        assert pair.rcmd("HGET", "h", "f2") == b"v2"
        assert link.lag_ops() == 0

    def test_converged_state_is_bit_identical(self, pair):
        seed_primary(pair, n=64)
        pair.start_replica()
        pair.wait_caught_up()
        prows = engine_rows(pair.primary._engine)
        rrows = engine_rows(pair.replica._engine)
        assert set(prows) == set(rrows)
        for name in prows:
            assert np.array_equal(prows[name], rrows[name]), name

    def test_seeded_replid_skips_full_resync(self, pair):
        """A link seeded with the primary's replid (the boot-bootstrap
        path: __main__ restores the snapshot itself, then hands the
        replid to the link) rides CONTINUE — no snapshot re-ship."""
        seed_primary(pair, n=8)
        replid = pair.pserver._repl_hub().repl_id
        link = pair.start_replica(replid=replid)
        pair.wait_caught_up()
        assert link.full_resyncs == 0
        assert link.partial_resyncs >= 1
        assert pair.rcmd("HGET", "h", "f1") == b"v1"

    def test_replica_rejects_writes(self, pair):
        seed_primary(pair, n=2)
        pair.start_replica()
        pair.wait_caught_up()
        reply = pair.rcmd("BF.ADD", "bf", "666")
        assert isinstance(reply, ReplyError) and reply.code == "READONLY"
        reply = pair.rcmd("SET", "k", "v")
        assert isinstance(reply, ReplyError) and reply.code == "READONLY"
        # Reads and admin stay open.
        assert pair.rcmd("PING") == b"PONG"
        assert pair.rcmd("DBSIZE") >= 1


class TestResyncLadder:
    def test_partial_resync_after_link_drop(self, pair):
        seed_primary(pair, n=10)
        link = pair.start_replica()
        pair.wait_caught_up()
        assert link.full_resyncs == 1
        # Sever the TCP leg out from under the link thread; writes land
        # on the primary while the replica is dark.
        link._sock.close()
        pair.pcmd("BF.ADD", "bf", "555")
        pair.pcmd("HSET", "h", "gap", "filled")
        pair.wait_caught_up()
        assert link.partial_resyncs >= 1
        assert link.full_resyncs == 1, (
            "reconnect must NOT re-ship the snapshot"
        )
        assert pair.rcmd("BF.EXISTS", "bf", "555") == 1
        assert pair.rcmd("HGET", "h", "gap") == b"filled"

    def test_psync_ladder_on_the_wire(self, pair):
        """RTPU.PSYNC: matching (replid, offset) → CONTINUE; '?' or a
        foreign replid → FULLRESYNC carrying a snapshot tar."""
        seed_primary(pair, n=4)
        hub_id = pair.pserver._repl_hub().repl_id
        head = pair.head()
        s = pair.sock(pair.pserver)
        reply = pair.cmd(s, "RTPU.PSYNC", hub_id, str(head))
        assert reply[0] == b"CONTINUE" and reply[1] == hub_id.encode()
        s2 = pair.sock(pair.pserver)
        reply = pair.cmd(s2, "RTPU.PSYNC", "?", "0")
        assert reply[0] == b"FULLRESYNC"
        assert reply[1] == hub_id.encode()
        assert int(reply[2]) >= 0  # snapshot cut seq
        assert len(reply[3]) > 0  # the tar payload
        s3 = pair.sock(pair.pserver)
        reply = pair.cmd(s3, "RTPU.PSYNC", "f" * 40, str(head))
        assert reply[0] == b"FULLRESYNC", "foreign replid must not CONTINUE"

    def test_psync_without_journal_is_refused(self, tmp_path):
        client = redisson_tpu.create(
            make_cfg(tmp_path, "nojournal", journal=False, snap=False)
        )
        server = RespServer(client, host="127.0.0.1", port=0)
        try:
            s = socket.create_connection((server.host, server.port), 5)
            try:
                (reply,) = exchange(s, [("RTPU.PSYNC", "?", "0")])
                assert isinstance(reply, ReplyError)
                assert reply.code == "NOJOURNAL"
            finally:
                s.close()
        finally:
            server.close()
            client.shutdown()


class TestFencesAndInfo:
    def test_wait_replica_ack_fence(self, pair):
        seed_primary(pair, n=4)
        pair.start_replica()
        pair.wait_caught_up()
        pair.pcmd("BF.ADD", "bf", "777")
        # WAIT 1 blocks until one replica acks the fence offset.
        assert pair.pcmd("WAIT", "1", "5000") == 1
        assert pair.rcmd("BF.EXISTS", "bf", "777") == 1
        # WAIT 0 never blocks; reports the acked-replica count.
        assert pair.pcmd("WAIT", "0", "0") >= 0

    def test_info_replication_both_ends(self, pair):
        seed_primary(pair, n=4)
        pair.start_replica()
        pair.wait_caught_up()
        pair.pcmd("BF.ADD", "bf", "778")
        assert pair.pcmd("WAIT", "1", "5000") == 1
        pinfo = pair.pcmd("INFO", "replication").decode()
        rinfo = pair.rcmd("INFO", "replication").decode()
        assert "role:master" in pinfo
        assert "connected_slaves:1" in pinfo
        assert "slave0:" in pinfo
        assert "master_replid:" in pinfo
        assert "role:slave" in rinfo
        assert "master_link_status:up" in rinfo
        hub_id = pair.pserver._repl_hub().repl_id
        assert hub_id in pinfo and hub_id in rinfo

    def test_hello_and_replconf_roles(self, pair):
        seed_primary(pair, n=2)
        pair.start_replica()
        pair.wait_caught_up()
        hello_p = pair.pcmd("HELLO")
        hello_r = pair.rcmd("HELLO")
        p_map = dict(zip(hello_p[::2], hello_p[1::2]))
        r_map = dict(zip(hello_r[::2], hello_r[1::2]))
        assert p_map[b"role"] == b"master"
        assert r_map[b"role"] == b"slave"

    def test_bounded_staleness_read_gate(self, pair):
        seed_primary(pair, n=4)
        link = pair.start_replica()
        pair.wait_caught_up()
        pair.replica.config.repl_max_staleness_ops = 5
        assert pair.rcmd("HGET", "h", "f1") == b"v1"  # lag 0: serves
        # Force the reported lag over the bound (the dispatch gate reads
        # lag_ops(); genuine lag accounting is asserted separately).
        link.lag_ops = lambda: 99
        reply = pair.rcmd("HGET", "h", "f1")
        assert isinstance(reply, ReplyError) and reply.code == "STALEREAD"
        # Unkeyed commands (health checks, INFO) are never staleness-gated.
        assert pair.rcmd("PING") == b"PONG"
        del link.lag_ops
        assert pair.rcmd("HGET", "h", "f1") == b"v1"

    def test_lag_accounting(self, pair):
        seed_primary(pair, n=4)
        link = pair.start_replica()
        pair.wait_caught_up()
        assert link.lag_ops() == 0
        link.master_offset = link.applied + 7
        assert link.lag_ops() == 7
        link.master_offset = link.applied
        assert link.lag_ops() == 0


class TestPromotion:
    def test_failover_promotes_replica_to_writable_primary(self, pair):
        seed_primary(pair, n=6)
        pair.start_replica()
        pair.wait_caught_up()
        assert pair.rcmd("FAILOVER") == b"OK"
        deadline = time.monotonic() + 5
        while pair.rserver.replica_link is not None:
            assert time.monotonic() < deadline, "link never detached"
            time.sleep(0.02)
        rinfo = pair.rcmd("INFO", "replication").decode()
        assert "role:master" in rinfo
        # The promoted node accepts writes and kept the replicated state.
        assert pair.rcmd("BF.ADD", "bf", "888") == 1
        assert pair.rcmd("HGET", "h", "f1") == b"v1"
        assert pair.rcmd("BF.EXISTS", "bf", "888") == 1


@pytest.mark.chaos
@pytest.mark.slow
class TestLinkFaultSoak:
    def test_replica_converges_through_lossy_corrupt_link(self, pair):
        """Satellite 2: 5% of REPLFETCH batches dropped, then 5%
        corrupted (one payload byte flipped on the wire — the replica's
        CRC check must reject the batch, not apply it), plus dropped
        ACKs.  After the fault window closes the replica must be
        BIT-IDENTICAL to the primary: faults are latency, never
        divergence."""
        seed_primary(pair, n=16)
        link = pair.start_replica()
        pair.wait_caught_up()
        chaos.inject("repl.stream", kind="error", rate=0.05, seed=7)
        chaos.inject("repl.ack", kind="error", rate=0.05, seed=11)
        for i in range(120):
            pair.pcmd("BF.ADD", "bf", str(1000 + i))
            if i % 10 == 0:
                pair.pcmd("HSET", "h", f"d{i}", str(i))
        chaos.inject("repl.stream", kind="corrupt", rate=0.05, seed=13)
        for i in range(120):
            pair.pcmd("BF.ADD", "bf", str(2000 + i))
            if i % 10 == 0:
                pair.pcmd("HSET", "h", f"c{i}", str(i))
        fired = chaos.counts()
        chaos.clear()
        pair.wait_caught_up(timeout_s=60.0)
        assert link.full_resyncs == 1, (
            "link faults must heal via retry/partial-resync, not a "
            f"snapshot re-ship (counts: {fired})"
        )
        prows = engine_rows(pair.primary._engine)
        rrows = engine_rows(pair.replica._engine)
        assert set(prows) == set(rrows)
        for name in prows:
            assert np.array_equal(prows[name], rrows[name]), name
        assert pair.rcmd("HGET", "h", "c110") == b"110"
