"""Read replication of hot tenants (SURVEY §2.4 replication row /
VERDICT r2 Missing #9): a replicated bloom filter keeps one copy per mesh
shard; reads rotate across copies, writes broadcast to all — results stay
bit-identical to the unreplicated filter.
"""

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config


@pytest.fixture
def client():
    c = redisson_tpu.create(
        Config().use_tpu_sketch(num_shards=8, min_bucket=64)
    )
    yield c
    c.shutdown()


@pytest.fixture
def host():
    c = redisson_tpu.create(Config())
    yield c
    c.shutdown()


class TestReplication:
    def test_replicate_and_read_consistency(self, client, host):
        bf = client.get_bloom_filter("rep")
        hf = host.get_bloom_filter("rep")
        bf.try_init(10_000, 0.01)
        hf.try_init(10_000, 0.01)
        pre = np.arange(2000, dtype=np.uint64)
        bf.add_all(pre)
        hf.add_all(pre)
        assert bf.set_replicated()
        assert bf.is_replicated()
        assert bf.set_replicated()  # idempotent
        # Pre-replication state was copied to every shard: any read
        # replica answers correctly, bit-identically to the host golden.
        probe = np.arange(0, 8000, 3, dtype=np.uint64)
        for _ in range(4):  # rotates across replicas between calls
            assert list(bf.contains_each(probe)) == list(hf.contains_each(probe))

    def test_writes_broadcast_to_all_replicas(self, client):
        bf = client.get_bloom_filter("rep-w")
        bf.try_init(10_000, 0.01)
        bf.set_replicated()
        post = np.arange(50_000, 52_000, dtype=np.uint64)
        newly = bf.add_all(post)
        assert newly >= 1990  # fresh keys report newly-added once each
        # Every read (whichever replica serves it) sees the writes.
        for _ in range(4):
            assert all(bf.contains_each(post))

    def test_mixed_batch_read_your_writes(self, client):
        bf = client.get_bloom_filter("rep-mix")
        bf.try_init(10_000, 0.01)
        bf.set_replicated()
        # Within one coalesced window: add then contains of the same key.
        fa = bf.add_all_async(np.asarray([777], np.uint64))
        fc = bf.contains_all_async(np.asarray([777], np.uint64))
        assert bool(fa.result()[0]) is True
        assert bool(fc.result()[0]) is True

    def test_replicas_occupy_every_shard(self, client):
        bf = client.get_bloom_filter("rep-place")
        bf.try_init(10_000, 0.01)
        bf.set_replicated()
        entry = client._engine.registry.lookup("rep-place")
        S = client._engine.executor.S
        assert len(entry.replica_rows) == S
        assert sorted(r % S for r in entry.replica_rows) == list(range(S))

    def test_delete_frees_all_replicas(self, client):
        bf = client.get_bloom_filter("rep-del")
        bf.try_init(10_000, 0.01)
        bf.set_replicated()
        entry = client._engine.registry.lookup("rep-del")
        rows = list(entry.replica_rows)
        pool = entry.pool
        assert bf.delete()
        for r in rows:
            assert r in pool._free

    def test_snapshot_preserves_replication(self, client, tmp_path):
        bf = client.get_bloom_filter("rep-snap")
        bf.try_init(10_000, 0.01)
        keys = np.arange(500, dtype=np.uint64)
        bf.add_all(keys)
        bf.set_replicated()
        client._engine.snapshot(str(tmp_path))
        c2 = redisson_tpu.create(
            Config().use_tpu_sketch(num_shards=8, min_bucket=64)
        )
        try:
            assert c2._engine.restore_snapshot(str(tmp_path))
            bf2 = c2.get_bloom_filter("rep-snap")
            assert bf2.is_replicated()
            assert all(bf2.contains_each(keys))
            # Replica rows are reserved: a new filter can't steal them.
            other = c2.get_bloom_filter("rep-snap-2")
            other.try_init(10_000, 0.01)
            e1 = c2._engine.registry.lookup("rep-snap")
            e2 = c2._engine.registry.lookup("rep-snap-2")
            assert e2.row not in e1.replica_rows
        finally:
            c2.shutdown()

    def test_single_device_replicate_is_noop(self, host):
        bf = host.get_bloom_filter("rep-host")
        bf.try_init(1000, 0.01)
        assert bf.set_replicated() is False
        assert bf.is_replicated() is False


class TestReplicationFence:
    def test_fence_redispatches_when_publish_races(self, client):
        """A writer that captured replica_rows=None before the publish
        must re-dispatch as a broadcast (post-submit re-check)."""
        eng = client._engine
        bf = client.get_bloom_filter("fence")
        bf.try_init(10_000, 0.01)
        entry = eng.registry.lookup("fence")
        calls = []
        eng._replication_fence(entry, False, lambda: calls.append(1))
        assert calls == []  # not replicated: nothing to do
        bf.set_replicated()
        eng._replication_fence(entry, False, lambda: calls.append(1))
        assert calls == [1]  # stale capture + published -> re-dispatch
        eng._replication_fence(entry, True, lambda: calls.append(1))
        assert calls == [1]  # fresh capture: no re-dispatch

    def test_concurrent_writes_during_replicate_no_false_negatives(self, client):
        """Stress the real race: writers add while set_replicated runs;
        afterwards every added key must be visible on EVERY replica."""
        import threading

        import numpy as np

        bf = client.get_bloom_filter("fence-stress")
        bf.try_init(50_000, 0.01)
        added = []
        stop = threading.Event()

        def writer(tid):
            i = 0
            while not stop.is_set() and i < 40:
                keys = np.arange(tid * 10_000 + i * 50,
                                 tid * 10_000 + i * 50 + 50, dtype=np.uint64)
                bf.add_all(keys)
                added.append(keys)
                i += 1

        threads = [threading.Thread(target=writer, args=(t,)) for t in range(3)]
        for t in threads:
            t.start()
        bf.set_replicated()
        stop.set()
        for t in threads:
            t.join()
        all_keys = np.concatenate(added)
        # Check MANY times: reads rotate across every replica row.
        for _ in range(8):
            assert all(bf.contains_each(all_keys)), "false negative on a replica"
