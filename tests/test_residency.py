"""Tiered sketch storage (ISSUE 14): the heat-based residency ladder.

Covers the heat tracker (fake clock — no DEBUG SLEEP-style waits), the
DEVICE ⇄ HOST ⇄ DISK transitions for every sketch kind (bit-exact
through the degraded-tier codecs), born-cold creation past the device
budget, the maintenance cycle (budget enforcement, admission-aware
promotion, host-bytes spill, quarantine reclaim), the RESP surface
(OBJECT FREQ/IDLETIME/ENCODING, CONFIG SET residency-*, INFO memory,
DEBUG RESIDENCY), chaos at the storage.spill/storage.load points, the
randomized differential soak (interleaved ops + forced transitions +
breaker degradation, every read equality-checked against the host
golden engine), mixed-tier snapshot/journal recovery, and the slow
kill -9 soak riding the crashchild harness with forced mid-stream
transitions.
"""

import os
import socket
import time

import numpy as np
import pytest

from redisson_tpu import chaos
from redisson_tpu.config import Config
from redisson_tpu.storage import DEVICE, DISK, HOST, HeatTracker


@pytest.fixture(autouse=True)
def _chaos_off():
    chaos.clear()
    chaos.reset_counts()
    yield
    chaos.clear()
    chaos.reset_counts()


def make_client(tmp_path=None, **tpu_kw):
    from redisson_tpu.client import RedissonTpuClient

    tpu_kw.setdefault("batch_window_us", 100)
    tpu_kw.setdefault("min_bucket", 64)
    if tmp_path is not None:
        tpu_kw.setdefault("residency_dir", str(tmp_path / "blobs"))
    cfg = Config().use_tpu_sketch(**tpu_kw)
    cfg.retry_attempts = 2
    cfg.retry_interval_ms = 5
    return RedissonTpuClient(cfg)


# -- heat tracker (fake clock) ------------------------------------------------


class _FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_heat_decays_by_half_life():
    clk = _FakeClock()
    h = HeatTracker(half_life_s=10.0, clock=clk)
    for _ in range(8):
        h.touch("a")
    assert h.heat("a") == pytest.approx(8.0)
    clk.t += 10.0
    assert h.heat("a") == pytest.approx(4.0)
    clk.t += 20.0
    assert h.heat("a") == pytest.approx(1.0)
    assert h.idle_s("a") == pytest.approx(30.0)
    assert h.heat("never") == 0.0 and h.idle_s("never") == 0.0


def test_heat_rename_drop_and_prune():
    clk = _FakeClock()
    h = HeatTracker(half_life_s=10.0, clock=clk, max_entries=8)
    h.touch("x", 5)
    h.rename("x", "y")
    assert h.heat("y") == pytest.approx(5.0) and h.heat("x") == 0.0
    h.drop("y")
    assert h.heat("y") == 0.0
    # Prune folds away the coldest half once past the bound.
    for i in range(6):
        h.touch(f"hot{i}", 10)
    for i in range(9):
        h.touch(f"cold{i}", 1)
    assert len(h) <= 8
    assert h.heat("hot0") > 0.0  # hottest survive


# -- ladder transitions, all kinds, bit-exact ---------------------------------


def _truth(eng, name):
    return np.asarray(eng._host_row(eng.registry.lookup(name))).copy()


def test_full_ladder_every_kind_bit_exact(tmp_path):
    cl = make_client(tmp_path)
    try:
        eng = cl._engine
        rm = eng.residency
        bf = cl.get_bloom_filter("bf")
        bf.try_init(2000, 0.01)
        bf.add_all([1, 2, 3])
        bs = cl.get_bit_set("bs")
        bs.set_many([5, 700], True)
        cms = cl.get_count_min_sketch("cms")
        cms.try_init(4, 512)
        cms.add(7, 3)
        h = cl.get_hyper_log_log("hll")
        h.add_all(list(range(200)))
        names = ["bf", "bs", "cms", "hll"]
        before = {n: _truth(eng, n) for n in names}
        for n in names:
            assert rm.demote(n), n
            e = eng.registry.lookup(n)
            assert e.row < 0 and e.residency == HOST
            assert np.array_equal(_truth(eng, n), before[n]), n
        for n in names:
            assert rm.spill(n), n
            assert eng.registry.lookup(n).residency == DISK
            assert n not in eng._mirrors
        # Reads on the DISK tier load the blob and serve bit-identical.
        assert bf.contains(1) and not bf.contains(999999)
        assert bs.get(5) and not bs.get(6)
        assert cms.estimate(7) >= 3
        h.count()
        for n in names:
            if eng.registry.lookup(n).residency == DISK:
                assert rm.load(n), n
            assert np.array_equal(_truth(eng, n), before[n]), n
        for n in names:
            assert rm.promote(n), n
            e = eng.registry.lookup(n)
            assert e.row >= 0 and e.residency == DEVICE
            assert np.array_equal(_truth(eng, n), before[n]), n
        st = rm.stats()
        assert st["demotions"] == 4 and st["promotions"] == 4
        assert st["spills"] == 4 and st["loads"] == 4
        # Quarantined rows recycle after a later drain.
        assert rm.reclaim() == 4
    finally:
        cl.shutdown()


def test_writes_on_every_tier_are_acked_and_kept(tmp_path):
    """Demoted is not degraded: mutations land on whatever tier the
    object occupies and survive the full ladder round trip."""
    cl = make_client(tmp_path)
    try:
        eng = cl._engine
        rm = eng.residency
        bf = cl.get_bloom_filter("bf")
        bf.try_init(2000, 0.01)
        bf.add(1)
        assert rm.demote("bf")
        bf.add(2)  # HOST-tier write
        assert eng.health.board.open_count() == 0
        assert not eng.health.any_degraded  # no breaker involved
        assert rm.spill("bf")
        bf.add(3)  # DISK-tier write: loads, then applies to the mirror
        assert eng.registry.lookup("bf").residency == HOST
        assert rm.promote("bf")
        for k in (1, 2, 3):
            assert bf.contains(k), k
        # Bitset size-class growth while demoted.
        bs = cl.get_bit_set("bs")
        bs.set(1, True)
        assert rm.demote("bs")
        bs.set(100_000, True)  # grows past the original class
        assert bs.get(100_000) and bs.get(1)
        assert rm.promote("bs")
        assert bs.get(100_000) and bs.get(1) and not bs.get(2)
    finally:
        cl.shutdown()


def test_demote_refuses_replicated_and_breaker_degraded(tmp_path):
    cl = make_client(tmp_path)
    try:
        eng = cl._engine
        rm = eng.residency
        bf = cl.get_bloom_filter("bf")
        bf.try_init(1000, 0.01)
        bf.add(1)
        # Breaker owns the kind: demote refuses (the mirror lifecycle
        # belongs to reconcile while degraded).
        orig = eng.health.degraded_kind
        try:
            eng.health.degraded_kind = lambda kind: kind == "bloom"
            assert not rm.demote("bf")
        finally:
            eng.health.degraded_kind = orig
        assert rm.demote("bf")
        assert rm.promote("bf")
    finally:
        cl.shutdown()


# -- born-cold creation + maintenance ----------------------------------------


def test_born_cold_past_budget_and_heat_promotion(tmp_path):
    cl = make_client(tmp_path)
    try:
        eng = cl._engine
        rm = eng.residency
        seed = cl.get_bloom_filter("warm")
        seed.try_init(500, 0.01)
        seed.add(1)
        rm.set_budget(device_rows=rm.device_rows_used())
        cold = cl.get_bloom_filter("cold")
        cold.try_init(500, 0.01)
        e = eng.registry.lookup("cold")
        assert e.row < 0 and e.residency == HOST  # born cold, no row
        cold.add(42)
        assert cold.contains(42) and not cold.contains(43)
        # Heat it: maintenance swaps it in against the colder tenant.
        for _ in range(40):
            cold.contains(42)
        out = rm.maintain()
        assert out["promoted"] >= 1
        assert eng.registry.lookup("cold").row >= 0
        assert eng.registry.lookup("warm").row < 0  # the cold victim
        assert cold.contains(42) and seed.contains(1)
    finally:
        cl.shutdown()


def test_maintenance_budget_and_spill_and_admission(tmp_path):
    cl = make_client(tmp_path)
    try:
        eng = cl._engine
        rm = eng.residency
        for i in range(6):
            bf = cl.get_bloom_filter(f"t{i}")
            bf.try_init(500, 0.01)
            bf.add(i)
        used = rm.device_rows_used()
        rm.set_budget(device_rows=max(1, used - 3))
        out = rm.maintain()
        assert out["demoted"] >= 3
        # Demoted rows sit QUARANTINED (still counted used) until a
        # later cycle's drain reclaims them — the no-stale-reads half
        # of the transition protocol.
        assert rm.reclaim() >= 3
        assert rm.device_rows_used() <= rm.device_rows
        # Host-bytes cap: everything demoted spills.
        rm.set_budget(max_host_bytes=1)
        out = rm.maintain()
        assert out["spilled"] >= 1
        assert rm.disk_objects() >= 1
        # Admission-blocked: promotion is deferred, never stormed.
        rm.promote_heat = 0.0
        blocked = {"v": True}
        rm._admission_blocked = lambda: blocked["v"]
        out = rm.maintain()
        assert out["promoted"] == 0
        blocked["v"] = False
    finally:
        cl.shutdown()


# -- chaos at the storage points ----------------------------------------------


def test_chaos_spill_and_load_fail_clean(tmp_path):
    from redisson_tpu.chaos import FaultInjected

    cl = make_client(tmp_path)
    try:
        eng = cl._engine
        rm = eng.residency
        bf = cl.get_bloom_filter("bf")
        bf.try_init(1000, 0.01)
        bf.add_all([1, 2])
        want = _truth(eng, "bf")
        assert rm.demote("bf")
        chaos.inject("storage.spill", kind="error", rate=1.0, seed=7)
        with pytest.raises(FaultInjected):
            rm.spill("bf")
        # Entry intact on the HOST tier, state unharmed.
        assert eng.registry.lookup("bf").residency == HOST
        assert np.array_equal(_truth(eng, "bf"), want)
        chaos.clear()
        assert rm.spill("bf")
        chaos.inject("storage.load", kind="error", rate=1.0, seed=7)
        with pytest.raises(FaultInjected):
            rm.load("bf")
        assert eng.registry.lookup("bf").residency == DISK
        chaos.clear()
        assert rm.load("bf")
        assert np.array_equal(_truth(eng, "bf"), want)
    finally:
        cl.shutdown()


def test_torn_blob_refuses_instead_of_serving_garbage(tmp_path):
    cl = make_client(tmp_path)
    try:
        eng = cl._engine
        rm = eng.residency
        bf = cl.get_bloom_filter("bf")
        bf.try_init(1000, 0.01)
        bf.add(1)
        assert rm.demote("bf") and rm.spill("bf")
        info = rm.disk_index()["bf"]
        path = os.path.join(rm.directory, info["file"])
        blob = open(path, "rb").read()
        mid = len(blob) // 2
        open(path, "wb").write(
            blob[:mid] + bytes([blob[mid] ^ 0xFF]) + blob[mid + 1:]
        )
        with pytest.raises(ValueError, match="CRC"):
            rm.load("bf")
    finally:
        cl.shutdown()


# -- identity ops across tiers ------------------------------------------------


def test_delete_rename_expire_drop_tier_state(tmp_path):
    cl = make_client(tmp_path)
    try:
        eng = cl._engine
        rm = eng.residency
        bf = cl.get_bloom_filter("a")
        bf.try_init(500, 0.01)
        bf.add(1)
        assert rm.demote("a") and rm.spill("a")
        assert eng.rename("a", "b")
        assert rm.disk_index().get("b") and not rm.disk_index().get("a")
        bf2 = cl.get_bloom_filter("b")
        assert bf2.contains(1)  # loaded from the renamed blob
        assert eng.delete("b")
        assert rm.disk_index() == {} and rm.host_objects() == 0
        assert "b" not in eng._mirrors
        # Expiry reaps tier state too.
        bf3 = cl.get_bloom_filter("c")
        bf3.try_init(500, 0.01)
        bf3.add(1)
        assert rm.demote("c")
        eng.expire_at("c", time.time() - 1.0)
        assert not eng.exists("c")
        assert "c" not in eng._mirrors and rm.host_objects() == 0
    finally:
        cl.shutdown()


# -- RESP surface -------------------------------------------------------------


def _resp(cl):
    from redisson_tpu.serve.resp import RespServer

    srv = RespServer(cl)
    s = socket.create_connection((srv.host, srv.port))

    def cmd(*args):
        from redisson_tpu.serve import wireutil

        return wireutil.exchange(
            s, [[str(a).encode() for a in args]]
        )[0]

    return srv, s, cmd


def test_object_introspection_rides_the_heat_tracker(tmp_path):
    cl = make_client(tmp_path)
    srv = s = None
    try:
        eng = cl._engine
        rm = eng.residency
        clk = _FakeClock()
        rm.heat = HeatTracker(half_life_s=10.0, clock=clk)
        srv, s, cmd = _resp(cl)
        cmd("BF.RESERVE", "bf", "0.01", "1000")
        for _ in range(6):
            cmd("BF.ADD", "bf", "1")
        assert cmd("OBJECT", "ENCODING", "bf") == b"device"
        # ISSUE 16 satellite: FREQ reports the redis 0-255 LOGARITHMIC
        # LFU scale — min(255, round(32*log2(1+h))) over the decayed
        # heat h.  ~7 touches -> h≈7 -> 96; three half-lives later
        # h≈0.9 -> ~30 (still >0: log scale compresses, it never lies
        # that a warm key is stone cold).
        hot_freq = cmd("OBJECT", "FREQ", "bf")
        assert 64 <= hot_freq <= 255
        clk.t += 30.0  # fake clock, no DEBUG SLEEP
        assert cmd("OBJECT", "IDLETIME", "bf") == 30
        cold_freq = cmd("OBJECT", "FREQ", "bf")
        assert cold_freq < hot_freq
        assert cold_freq <= 32
        assert cmd("DEBUG", "RESIDENCY", "DEMOTE", "bf") == 1
        assert cmd("OBJECT", "ENCODING", "bf") == b"host"
        assert cmd("DEBUG", "RESIDENCY", "SPILL", "bf") == 1
        assert cmd("OBJECT", "ENCODING", "bf") == b"disk"
        assert cmd("DEBUG", "RESIDENCY", "PROMOTE", "bf") == 1
        assert cmd("OBJECT", "ENCODING", "bf") == b"device"
        # Grid kinds keep the classic encodings.
        cmd("XADD", "st", "*", "f", "v")
        assert cmd("OBJECT", "ENCODING", "st") == b"stream"
    finally:
        if s is not None:
            s.close()
            srv.close()
        cl.shutdown()


def test_resp_config_and_info_surface(tmp_path):
    from redisson_tpu.serve.wireutil import ReplyError

    cl = make_client(tmp_path)
    srv = s = None
    try:
        srv, s, cmd = _resp(cl)
        got = dict(zip(*[iter(cmd("CONFIG", "GET", "residency-*"))] * 2))
        assert got[b"residency-device-rows"] == b"0"
        assert cmd("CONFIG", "SET", "residency-device-rows", "8") == b"OK"
        assert cl._engine.residency.device_rows == 8
        assert cl._engine.residency._thread is not None  # budget armed it
        bad = cmd("CONFIG", "SET", "residency-max-host-bytes", "-3")
        assert isinstance(bad, ReplyError)
        bad = cmd("CONFIG", "SET", "residency-device-rows", "x")
        assert isinstance(bad, ReplyError)
        info = cmd("INFO", "memory").decode()
        for line in ("residency_device_rows_budget:8",
                     "residency_host_objects:", "residency_disk_bytes:",
                     "residency_promotions:"):
            assert line in info, line
        tick = cmd("DEBUG", "RESIDENCY", "TICK")
        assert any(r.startswith(b"reclaimed") for r in tick)
    finally:
        if s is not None:
            s.close()
            srv.close()
        cl.shutdown()


def test_object_is_shed_exempt():
    from redisson_tpu.serve.resp import _SHED_EXEMPT

    assert "OBJECT" in _SHED_EXEMPT


# -- near-cache reach satellite (stream/geo scalars) --------------------------


def test_stream_and_geo_scalars_ride_the_near_cache(tmp_path):
    cl = make_client(tmp_path)
    try:
        nc = cl._engine.nearcache
        st = cl.get_stream("s1")
        st.add({b"f": b"v"})
        assert st.size() == 1  # miss, installs
        base_hits = nc.hits
        assert st.size() == 1  # hit
        assert nc.hits == base_hits + 1
        st.add({b"f": b"v2"})  # bump retires the cached scalar
        assert st.size() == 2
        st.remove(st.last_id())
        assert st.size() == 1
        geo = cl.get_geo("g1")
        geo.add(13.361389, 38.115556, b"palermo")
        geo.add(15.087269, 37.502669, b"catania")
        d1 = geo.dist(b"palermo", b"catania", "km")
        hits0 = nc.hits
        assert geo.dist(b"palermo", b"catania", "km") == d1  # hit
        assert nc.hits == hits0 + 1
        geo.add(15.0, 37.0, b"catania")  # move: epoch bump
        d2 = geo.dist(b"palermo", b"catania", "km")
        assert d2 != d1
        p = geo.pos(b"palermo")
        p[b"palermo"] = (0.0, 0.0)  # caller mutation must not poison
        assert geo.pos(b"palermo")[b"palermo"] != (0.0, 0.0)
        # Store-level delete invalidates the grid tenant.
        st.delete()
        assert st.size() == 0
        # TTL semantics survive the cache (review finding): cached
        # scalars carry the key's deadline — expiry is observed at
        # READ time, not at the next sweep; EXPIRE/PERSIST on a
        # cached key retire the stale deadline through the store hook.
        st2 = cl.get_stream("s2")
        st2.add({b"f": b"v"})
        assert st2.size() == 1      # installs (no TTL yet)
        st2.expire(0.05)            # EXPIRE invalidates the cached pair
        assert st2.size() == 1      # re-installs WITH the deadline
        time.sleep(0.08)
        assert st2.size() == 0      # deadline observed by the cached read
        assert not st2.is_exists()
    finally:
        cl.shutdown()


# -- randomized differential soak --------------------------------------------


def _flap(fn, attempts=8):
    """Ride out breaker flaps (the test_nearcache soak idiom): a
    degraded-window op may fail typed while the breaker opens — the
    chaos error fires PRE-mutation, so a failed op never applied and a
    retry applies exactly once."""
    for _ in range(attempts - 1):
        try:
            return fn()
        except Exception:
            time.sleep(0.05)
    return fn()


def test_differential_soak_vs_golden_with_forced_transitions(tmp_path):
    """The acceptance soak: interleaved ops + forced promote / demote /
    spill / load + breaker degradation on the SAME objects, every read
    equality-checked against the host golden engine — zero stale reads,
    zero acked-write loss."""
    import random

    import redisson_tpu

    rng = random.Random(20260804)
    gold = redisson_tpu.create(Config())
    cl = make_client(
        tmp_path, breaker_failure_threshold=2, breaker_open_ms=400
    )
    try:
        eng = cl._engine
        rm = eng.residency
        tb, gb = (x.get_bloom_filter("soak-bf") for x in (cl, gold))
        for h in (tb, gb):
            h.try_init(20_000, 0.01)
        tbs, gbs = (x.get_bit_set("soak-bs") for x in (cl, gold))
        tcm, gcm = (
            x.get_count_min_sketch("soak-cms") for x in (cl, gold)
        )
        for h in (tcm, gcm):
            h.try_init(4, 512)
        th, gh = (x.get_hyper_log_log("soak-hll") for x in (cl, gold))
        names = ("soak-bf", "soak-bs", "soak-cms", "soak-hll")
        K = 2000
        degraded_until = 0.0
        for step in range(300):
            roll = rng.random()
            if roll < 0.12:
                n = names[rng.randrange(4)]
                verb = rng.randrange(4)
                if verb == 0:
                    rm.demote(n)
                elif verb == 1:
                    rm.promote(n)
                elif verb == 2:
                    rm.demote(n)
                    rm.spill(n)
                else:
                    rm.load(n)
            elif roll < 0.15 and not degraded_until:
                # Breaker degradation on the same objects (demoted is
                # NOT degraded — the soak exercises both on one
                # keyspace).
                chaos.inject(
                    "dispatch.bloom_mixed", kind="error", rate=1.0,
                    seed=step,
                )
                degraded_until = time.monotonic() + 0.2
            elif roll < 0.40:
                ks = [rng.randrange(K) for _ in range(6)]
                _flap(lambda: tb.add_all(ks))
                gb.add_all(ks)
            elif roll < 0.55:
                idx = [rng.randrange(4096) for _ in range(4)]
                val = rng.random() < 0.8
                _flap(lambda: tbs.set_many(idx, val))
                gbs.set_many(idx, val)
            elif roll < 0.65:
                ks = [rng.randrange(K) for _ in range(4)]
                w = [1 + rng.randrange(4) for _ in range(4)]
                _flap(lambda: tcm.add_all(ks, w))
                gcm.add_all(ks, w)
            elif roll < 0.72:
                ks = [rng.randrange(K) for _ in range(8)]
                _flap(lambda: th.add_all(ks))
                gh.add_all(ks)
            else:
                ks = [rng.randrange(K) for _ in range(8)]
                got = _flap(lambda: tb.contains_each(ks))
                want = gb.contains_each(ks)
                assert np.array_equal(
                    np.asarray(got, bool), np.asarray(want, bool)
                ), f"step {step}: stale bloom read"
                idx = [rng.randrange(4096) for _ in range(4)]
                got = _flap(lambda: tbs.get_many(idx))
                want = gbs.get_many(idx)
                assert np.array_equal(
                    np.asarray(got, bool), np.asarray(want, bool)
                ), f"step {step}: stale bitset read"
                est_t = _flap(lambda: tcm.estimate_all(ks))
                est_g = gcm.estimate_all(ks)
                assert np.array_equal(
                    np.asarray(est_t, np.int64),
                    np.asarray(est_g, np.int64),
                ), f"step {step}: stale cms read"
                assert _flap(lambda: th.count()) == gh.count(), (
                    f"step {step}: stale hll count"
                )
            if degraded_until and time.monotonic() > degraded_until:
                chaos.clear()
                degraded_until = 0.0
        chaos.clear()
        # Breaker may still be open from the last window: wait it out
        # so the final comparison sees reconciled state, then compare
        # the WHOLE keyspace (zero acked-write loss).
        deadline = time.monotonic() + 8.0
        while eng.health.any_degraded and time.monotonic() < deadline:
            time.sleep(0.05)
        for n in names:
            rm.load(n)
            rm.promote(n)
        ks = list(range(K))
        assert np.array_equal(
            np.asarray(_flap(lambda: tb.contains_each(ks)), bool),
            np.asarray(gb.contains_each(ks), bool),
        )
        idx = list(range(4096))
        assert np.array_equal(
            np.asarray(_flap(lambda: tbs.get_many(idx)), bool),
            np.asarray(gbs.get_many(idx), bool),
        )
        assert np.array_equal(
            np.asarray(_flap(lambda: tcm.estimate_all(ks)), np.int64),
            np.asarray(gcm.estimate_all(ks), np.int64),
        )
        st = rm.stats()
        assert st["demotions"] > 0 and st["promotions"] > 0
        assert st["spills"] > 0
    finally:
        chaos.clear()
        cl.shutdown()
        gold.shutdown()


# -- snapshot / recovery across tiers -----------------------------------------


def _mk_durable(tmp_path):
    from redisson_tpu.client import RedissonTpuClient

    cfg = Config().use_tpu_sketch(
        min_bucket=64, batch_window_us=100,
        residency_dir=str(tmp_path / "blobs"),
    )
    cfg.snapshot_dir = str(tmp_path / "snap")
    cfg.journal_dir = str(tmp_path / "journal")
    cfg.journal_fsync = "always"
    cfg.retry_attempts = 2
    cfg.retry_interval_ms = 5
    return RedissonTpuClient(cfg)


def test_mixed_tier_recovery_bit_identical(tmp_path):
    """A DEVICE + HOST + DISK population snapshots, takes post-snapshot
    journaled writes on every tier, and a fresh engine recovers every
    object bit-identically — the DISK sketch restoring as DISK and
    loading without a device write."""
    cl = _mk_durable(tmp_path)
    eng = cl._engine
    rm = eng.residency
    for n in ("dev", "host", "disk", "disk-idle"):
        bf = cl.get_bloom_filter(n)
        bf.try_init(1000, 0.01)
        bf.add_all([1, 2])
    assert rm.demote("host")
    assert rm.demote("disk") and rm.spill("disk")
    assert rm.demote("disk-idle") and rm.spill("disk-idle")
    eng.snapshot(str(tmp_path / "snap"))
    cl.get_bloom_filter("dev").add(10)
    cl.get_bloom_filter("host").add(20)
    cl.get_bloom_filter("disk").add(30)  # loads → HOST, journaled
    truth = {
        n: _truth(eng, n) for n in ("dev", "host", "disk", "disk-idle")
    }
    # Abandon without shutdown (a clean shutdown would re-snapshot).
    j = eng.journal
    eng.journal = None
    j.close()
    eng.config.snapshot_dir = None
    cl.config.snapshot_dir = None
    cl.shutdown()

    cl2 = _mk_durable(tmp_path)
    try:
        eng2 = cl2._engine
        e_idle = eng2.registry.lookup("disk-idle")
        # Untouched-by-tail DISK sketch restores ON the disk tier.
        assert e_idle.residency == DISK and e_idle.row < 0
        for n, want in truth.items():
            got = _truth(eng2, n)
            assert np.array_equal(got, want), n
        assert cl2.get_bloom_filter("disk").contains(30)
        assert cl2.get_bloom_filter("host").contains(20)
        assert cl2.get_bloom_filter("dev").contains(10)
    finally:
        cl2.shutdown()


def test_blob_gc_never_deletes_snapshot_referenced_files(tmp_path):
    cl = _mk_durable(tmp_path)
    try:
        eng = cl._engine
        rm = eng.residency
        bf = cl.get_bloom_filter("bf")
        bf.try_init(1000, 0.01)
        bf.add(1)
        assert rm.demote("bf") and rm.spill("bf")
        blob1 = rm.disk_index()["bf"]["file"]
        eng.snapshot(str(tmp_path / "snap"))  # snapshot references blob1
        # Load + re-spill: blob1 retires but may NOT be GC'd (the
        # latest snapshot still names it; a crash would restore from
        # it and replay the tail).
        assert rm.load("bf")
        bf.add(2)
        assert rm.spill("bf")
        blob2 = rm.disk_index()["bf"]["file"]
        assert blob2 != blob1
        rm.gc_blobs()
        assert os.path.exists(os.path.join(rm.directory, blob1))
        # After the NEXT snapshot (referencing blob2), blob1 may go.
        eng.snapshot(str(tmp_path / "snap"))
        rm.gc_blobs()
        assert not os.path.exists(os.path.join(rm.directory, blob1))
        assert os.path.exists(os.path.join(rm.directory, blob2))
    finally:
        cl.shutdown()


# -- kill -9 soak with forced mid-stream transitions (slow) -------------------


@pytest.mark.slow
@pytest.mark.chaos
def test_kill9_residency_soak_recovers_bit_identical(tmp_path):
    """The tiered-soak CI job's core: the crashchild applies a
    deterministic op stream while FORCING demote/spill/promote cycles
    every few ops; a SIGKILL lands mid-stream (possibly mid-demotion
    or mid-spill), and recovery must restore a state bit-identical to
    a golden engine fed an acked-covering prefix — across whatever
    tier each object died in."""
    import random
    import signal
    import subprocess
    import sys

    from redisson_tpu.chaos import crashchild

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    seed = random.randrange(1 << 30)
    ops = 240
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "redisson_tpu.chaos.crashchild",
            "--dir", str(tmp_path), "--fsync", "always",
            "--seed", str(seed), "--ops", str(ops),
            "--residency-every", "7",
        ],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        cwd=repo, env=env, text=True,
    )
    acked = {}
    first_ack = None
    finished = False
    try:
        for line in proc.stdout:
            line = line.strip()
            if line.startswith("ACK "):
                _t, idx, ts = line.split()
                acked[int(idx)] = float(ts)
                if first_ack is None:
                    first_ack = time.monotonic()
                if time.monotonic() - first_ack >= 0.5:
                    os.kill(proc.pid, signal.SIGKILL)
                    break
            elif line == "DONE":
                finished = True
                os.kill(proc.pid, signal.SIGKILL)
                break
        for line in proc.stdout:
            line = line.strip()
            if line.startswith("ACK ") and len(line.split()) == 3:
                _t, idx, ts = line.split()
                acked[int(idx)] = float(ts)
            elif line == "DONE":
                finished = True
    finally:
        proc.stdout.close()
        proc.wait(timeout=30)
    assert acked, "child never acked a write"
    max_acked = max(acked)

    # Recover (tiers restore from the snapshot-less journal lineage —
    # residency state is perf state; the JOURNAL carries every acked
    # write whatever tier served it).
    rec = crashchild.build_client(str(tmp_path), "always", residency=True)
    eng = rec._engine
    eng._drain()
    rows = {
        e.name: np.asarray(eng._host_row(e)).copy()
        for e in eng.registry.entries()
    }
    eng.config.snapshot_dir = None
    rec.config.snapshot_dir = None
    j = eng.journal
    if j is not None:
        eng.journal = None
        j.close()
    rec.shutdown()
    assert rows, "recovery produced an empty keyspace"

    # Golden match: a plain engine (no residency) fed the same stream.
    class _Matched(Exception):
        def __init__(self, r):
            self.r = r

    import redisson_tpu as _rt
    from redisson_tpu.codecs import LongCodec

    gcfg = Config().set_codec(LongCodec()).use_tpu_sketch(min_bucket=64)
    golden_cl = _rt.create(gcfg)
    geng = golden_cl._engine

    def same():
        geng._drain()
        got = {
            e.name: np.asarray(geng._host_row(e))
            for e in geng.registry.entries()
        }
        if set(got) != set(rows):
            return False
        return all(np.array_equal(got[n], rows[n]) for n in got)

    lower = max_acked + 1
    matched = None

    def ack(i):
        nonlocal matched
        if i + 1 >= lower and matched is None and same():
            raise _Matched(i + 1)

    try:
        crashchild.apply_ops(golden_cl, seed, ops, ack=ack)
        if matched is None and same():
            matched = ops
    except _Matched as mm:
        matched = mm.r
    finally:
        golden_cl.shutdown()
    assert matched is not None, (
        f"recovered state matches no acked-covering prefix "
        f"(max_acked={max_acked}, finished={finished})"
    )
