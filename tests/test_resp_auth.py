"""Front-door auth (round-5 VERDICT item 4): requirepass config key,
AUTH + HELLO AUTH enforcement, pre-auth command rejection."""

import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.serve.resp import RespServer

from test_resp_server import RespClient

PW = "sekret-pw"


@pytest.fixture
def locked():
    client = redisson_tpu.create(
        Config().use_tpu_sketch(min_bucket=64).set_requirepass(PW)
    )
    # Scripting enabled: requirepass is set (TestScriptsOnLockedServer
    # exercises EVAL through the auth gate).
    server = RespServer(client, enable_python_scripts=True)
    yield server
    server.close()
    client.shutdown()


class TestRequirepass:
    def test_pre_auth_commands_refused(self, locked):
        c = RespClient(locked.host, locked.port)
        try:
            for cmd in (("PING",), ("GET", "k"), ("SET", "k", "v"),
                        ("FLUSHALL",), ("SUBSCRIBE", "ch"), ("DBSIZE",)):
                with pytest.raises(RuntimeError, match="NOAUTH"):
                    c.cmd(*cmd)
        finally:
            c.close()

    def test_wrong_password(self, locked):
        c = RespClient(locked.host, locked.port)
        try:
            with pytest.raises(RuntimeError, match="WRONGPASS"):
                c.cmd("AUTH", "nope")
            with pytest.raises(RuntimeError, match="NOAUTH"):
                c.cmd("PING")  # still locked after the failed attempt
        finally:
            c.close()

    def test_right_password_unlocks(self, locked):
        c = RespClient(locked.host, locked.port)
        try:
            assert c.cmd("AUTH", PW) == "OK"
            assert c.cmd("PING") == "PONG"
            assert c.cmd("SET", "k", "v") == "OK"
            assert c.cmd("GET", "k") == b"v"
        finally:
            c.close()

    def test_two_arg_auth_default_user(self, locked):
        c = RespClient(locked.host, locked.port)
        try:
            with pytest.raises(RuntimeError, match="WRONGPASS"):
                c.cmd("AUTH", "admin", PW)  # only 'default' exists
            assert c.cmd("AUTH", "default", PW) == "OK"
            assert c.cmd("PING") == "PONG"
        finally:
            c.close()

    def test_hello_auth(self, locked):
        c = RespClient(locked.host, locked.port)
        try:
            with pytest.raises(RuntimeError, match="NOAUTH"):
                c.cmd("HELLO", "2")  # HELLO without AUTH: refused
            with pytest.raises(RuntimeError, match="WRONGPASS"):
                c.cmd("HELLO", "2", "AUTH", "default", "bad")
            reply = c.cmd("HELLO", "2", "AUTH", "default", PW)
            assert b"server" in reply
            assert c.cmd("PING") == "PONG"
        finally:
            c.close()

    def test_quit_allowed_pre_auth(self, locked):
        c = RespClient(locked.host, locked.port)
        try:
            assert c.cmd("QUIT") == "OK"
        finally:
            c.close()

    def test_auth_is_per_connection(self, locked):
        c1 = RespClient(locked.host, locked.port)
        c2 = RespClient(locked.host, locked.port)
        try:
            assert c1.cmd("AUTH", PW) == "OK"
            with pytest.raises(RuntimeError, match="NOAUTH"):
                c2.cmd("PING")  # c1's auth must not leak to c2
        finally:
            c1.close()
            c2.close()


class TestOpenServer:
    def test_no_password_auth_errors_like_redis(self):
        client = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
        server = RespServer(client)
        c = RespClient(server.host, server.port)
        try:
            assert c.cmd("PING") == "PONG"  # open server: no gate
            with pytest.raises(RuntimeError, match="no password is set"):
                c.cmd("AUTH", "whatever")
        finally:
            c.close()
            server.close()
            client.shutdown()

    def test_requirepass_roundtrips_through_config_dict(self):
        cfg = Config().set_requirepass("p1")
        assert Config.from_dict(cfg.to_dict()).requirepass == "p1"


class TestScriptsOnLockedServer:
    def test_eval_bridge_works_after_auth(self, locked):
        """The script bridge's internal ctx must count as authed — the
        invoking connection already passed the gate (regression: the
        NOAUTH gate briefly broke every redis.call)."""
        c = RespClient(locked.host, locked.port)
        try:
            assert c.cmd("AUTH", PW) == "OK"
            c.cmd("SET", "sk", "sv")
            assert c.cmd(
                "EVAL", "redis.call('GET', KEYS[0])", 1, "sk"
            ) == b"sv"
        finally:
            c.close()

    def test_reset_allowed_pre_auth(self, locked):
        c = RespClient(locked.host, locked.port)
        try:
            assert c.cmd("RESET") == "RESET"  # pooled-client pattern
            with pytest.raises(RuntimeError, match="NOAUTH"):
                c.cmd("PING")
        finally:
            c.close()

    def test_reset_deauthenticates(self, locked):
        c = RespClient(locked.host, locked.port)
        try:
            assert c.cmd("AUTH", PW) == "OK"
            assert c.cmd("PING") == "PONG"
            assert c.cmd("RESET") == "RESET"
            with pytest.raises(RuntimeError, match="NOAUTH"):
                c.cmd("PING")  # RESET dropped the auth
        finally:
            c.close()
