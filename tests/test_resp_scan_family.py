"""Scan-family RESP commands (round-5 VERDICT item 9): HSCAN/SSCAN/
ZSCAN with cursor resume, ZUNIONSTORE/ZINTERSTORE, ZRANGEBYLEX."""

import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.serve.resp import RespServer

from test_resp_server import RespClient


@pytest.fixture
def resp():
    client = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    server = RespServer(client)
    conn = RespClient(server.host, server.port)
    yield conn
    conn.close()
    server.close()
    client.shutdown()


def _scan_all(conn, cmd, key, *opts):
    """Drive a cursor to exhaustion, return the concatenated items."""
    cursor, items = "0", []
    pages = 0
    while True:
        cur, page = conn.cmd(cmd, key, cursor, *opts)
        items.extend(page)
        pages += 1
        cursor = cur.decode()
        if cursor == "0":
            return items, pages


class TestHscan:
    def test_cursor_resume(self, resp):
        for i in range(25):
            resp.cmd("HSET", "h", f"f{i:02}", f"v{i}")
        items, pages = _scan_all(resp, "HSCAN", "h", "COUNT", 7)
        assert pages > 1  # really paged
        got = dict(zip(items[::2], items[1::2]))
        assert got == {f"f{i:02}".encode(): f"v{i}".encode()
                       for i in range(25)}

    def test_match_and_novalues(self, resp):
        for i in range(12):
            resp.cmd("HSET", "h2", f"a{i}", i)
            resp.cmd("HSET", "h2", f"b{i}", i)
        items, _ = _scan_all(resp, "HSCAN", "h2", "MATCH", "a*",
                             "COUNT", 5, "NOVALUES")
        assert sorted(items) == sorted(f"a{i}".encode() for i in range(12))

    def test_keys_present_throughout_all_returned(self, resp):
        """The SCAN guarantee: a concurrent delete of already-returned
        fields must not hide the others."""
        for i in range(20):
            resp.cmd("HSET", "h3", f"f{i:02}", i)
        cur, page1 = resp.cmd("HSCAN", "h3", 0, "COUNT", 5)
        for f in page1[::2]:
            resp.cmd("HDEL", "h3", f)
        rest, _ = _scan_all_from(resp, "HSCAN", "h3", cur.decode(),
                                 "COUNT", 5)
        survivors = {f"f{i:02}".encode() for i in range(20)} - set(page1[::2])
        assert set(rest[::2]) == survivors


def _scan_all_from(conn, cmd, key, cursor, *opts):
    items, pages = [], 0
    while True:
        cur, page = conn.cmd(cmd, key, cursor, *opts)
        items.extend(page)
        pages += 1
        cursor = cur.decode()
        if cursor == "0":
            return items, pages


class TestSscanZscan:
    def test_sscan(self, resp):
        for i in range(23):
            resp.cmd("SADD", "s", f"m{i:02}")
        items, pages = _scan_all(resp, "SSCAN", "s", "COUNT", 6)
        assert pages > 1
        assert sorted(items) == sorted(f"m{i:02}".encode() for i in range(23))

    def test_zscan(self, resp):
        for i in range(15):
            resp.cmd("ZADD", "z", i * 1.5, f"m{i:02}")
        items, pages = _scan_all(resp, "ZSCAN", "z", "COUNT", 4)
        assert pages > 1
        got = dict(zip(items[::2], items[1::2]))
        assert got[b"m02"] == b"3" and got[b"m01"] == b"1.5"
        assert len(got) == 15

    def test_cursor_wrong_command_terminates(self, resp):
        for i in range(20):
            resp.cmd("SADD", "s2", f"m{i}")
            resp.cmd("HSET", "h9", f"f{i}", i)
        cur, _ = resp.cmd("SSCAN", "s2", 0, "COUNT", 5)
        assert cur != b"0"
        # replaying an SSCAN cursor against HSCAN: terminated, not junk
        cur2, page = resp.cmd("HSCAN", "h9", int(cur), "COUNT", 5)
        assert cur2 == b"0" and page == []


class TestZsetStores:
    def test_zunionstore_weights_aggregate(self, resp):
        resp.cmd("ZADD", "za", 1, "a", 2, "b")
        resp.cmd("ZADD", "zb", 10, "b", 20, "c")
        assert resp.cmd("ZUNIONSTORE", "dest", 2, "za", "zb") == 3
        rows = resp.cmd("ZRANGE", "dest", 0, -1, "WITHSCORES")
        got = dict(zip(rows[::2], rows[1::2]))
        assert got == {b"a": b"1", b"b": b"12", b"c": b"20"}

        assert resp.cmd("ZUNIONSTORE", "dest", 2, "za", "zb",
                        "WEIGHTS", 2, 1, "AGGREGATE", "MAX") == 3
        rows = resp.cmd("ZRANGE", "dest", 0, -1, "WITHSCORES")
        got = dict(zip(rows[::2], rows[1::2]))
        assert got == {b"a": b"2", b"b": b"10", b"c": b"20"}

    def test_zinterstore(self, resp):
        resp.cmd("ZADD", "zi1", 1, "a", 2, "b", 3, "c")
        resp.cmd("ZADD", "zi2", 10, "b", 10, "c", 10, "d")
        assert resp.cmd("ZINTERSTORE", "idest", 2, "zi1", "zi2",
                        "AGGREGATE", "MIN") == 2
        rows = resp.cmd("ZRANGE", "idest", 0, -1, "WITHSCORES")
        got = dict(zip(rows[::2], rows[1::2]))
        assert got == {b"b": b"2", b"c": b"3"}

    def test_store_replaces_dest(self, resp):
        resp.cmd("ZADD", "dst", 99, "stale")
        resp.cmd("ZADD", "zsrc", 1, "x")
        assert resp.cmd("ZUNIONSTORE", "dst", 1, "zsrc") == 1
        assert resp.cmd("ZRANGE", "dst", 0, -1) == [b"x"]


class TestZrangebylex:
    def test_ranges(self, resp):
        for m in ("a", "b", "c", "d", "e"):
            resp.cmd("ZADD", "lex", 0, m)
        assert resp.cmd("ZRANGEBYLEX", "lex", "-", "+") == [
            b"a", b"b", b"c", b"d", b"e"
        ]
        assert resp.cmd("ZRANGEBYLEX", "lex", "[b", "[d") == [b"b", b"c", b"d"]
        assert resp.cmd("ZRANGEBYLEX", "lex", "(b", "(d") == [b"c"]
        assert resp.cmd("ZRANGEBYLEX", "lex", "-", "(c") == [b"a", b"b"]
        assert resp.cmd("ZRANGEBYLEX", "lex", "+", "-") == []

    def test_limit(self, resp):
        for m in ("a", "b", "c", "d", "e"):
            resp.cmd("ZADD", "lex2", 0, m)
        assert resp.cmd("ZRANGEBYLEX", "lex2", "-", "+",
                        "LIMIT", 1, 2) == [b"b", b"c"]

    def test_bad_bound_errors(self, resp):
        resp.cmd("ZADD", "lex3", 0, "a")
        with pytest.raises(RuntimeError, match="not valid string range"):
            resp.cmd("ZRANGEBYLEX", "lex3", "a", "+")


class TestReviewFixes:
    def test_zunionstore_short_weights_errors(self, resp):
        resp.cmd("ZADD", "wa", 1, "a")
        resp.cmd("ZADD", "wb", 1, "b")
        with pytest.raises(RuntimeError, match="syntax error"):
            resp.cmd("ZUNIONSTORE", "wd", 2, "wa", "wb", "WEIGHTS", 2)

    def test_zrangebylex_negative_count_means_all(self, resp):
        for m in ("a", "b", "c"):
            resp.cmd("ZADD", "lex9", 0, m)
        assert resp.cmd("ZRANGEBYLEX", "lex9", "-", "+",
                        "LIMIT", 0, -1) == [b"a", b"b", b"c"]
        assert resp.cmd("ZRANGEBYLEX", "lex9", "-", "+",
                        "LIMIT", 1, -1) == [b"b", b"c"]
