"""RESP front door (SURVEY.md §2.4 comm row): a raw RESP2 client drives
the engine's keyspace and sketch objects over TCP."""

import socket

import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.serve.resp import RespServer


class RespClient:
    """Minimal RESP2 client (what redis-py does on the wire)."""

    def __init__(self, host, port, timeout=10):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._buf = b""

    def cmd(self, *args):
        out = b"*" + str(len(args)).encode() + b"\r\n"
        for a in args:
            if not isinstance(a, bytes):
                a = str(a).encode()
            out += b"$" + str(len(a)).encode() + b"\r\n" + a + b"\r\n"
        self._sock.sendall(out)
        return self._read_reply()

    def _recv(self):
        data = self._sock.recv(65536)
        if not data:
            raise ConnectionError("connection closed by server")
        self._buf += data

    def _line(self):
        while b"\r\n" not in self._buf:
            self._recv()
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _exact(self, n):
        while len(self._buf) < n + 2:
            self._recv()
        out, self._buf = self._buf[:n], self._buf[n + 2:]
        return out

    def _read_reply(self):
        line = self._line()
        t, body = line[:1], line[1:]
        if t == b"+":
            return body.decode()
        if t == b"-":
            raise RuntimeError(body.decode())
        if t == b":":
            return int(body)
        if t == b"$":
            n = int(body)
            return None if n < 0 else self._exact(n)
        if t == b"*":
            n = int(body)
            if n < 0:
                return None  # null array (e.g. BLPOP timeout)
            return [self._read_reply() for _ in range(n)]
        raise RuntimeError(f"bad reply type {t!r}")

    def close(self):
        self._sock.close()


@pytest.fixture
def resp():
    client = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    server = RespServer(client)
    conn = RespClient(server.host, server.port)
    yield conn
    conn.close()
    server.close()
    client.shutdown()


class TestRespFrontDoor:
    def test_ping_echo(self, resp):
        assert resp.cmd("PING") == "PONG"
        assert resp.cmd("ECHO", "hello") == b"hello"

    def test_strings_and_keys(self, resp):
        assert resp.cmd("SET", "k", "v") == "OK"
        assert resp.cmd("GET", "k") == b"v"
        assert resp.cmd("EXISTS", "k") == 1
        assert resp.cmd("DBSIZE") == 1
        assert resp.cmd("DEL", "k") == 1
        assert resp.cmd("GET", "k") is None

    def test_expire_ttl(self, resp):
        resp.cmd("SET", "e", "v", "EX", "30")
        ttl = resp.cmd("TTL", "e")
        assert 0 < ttl <= 30
        assert resp.cmd("PERSIST", "e") == 1
        assert resp.cmd("TTL", "e") == -1

    def test_bitmaps(self, resp):
        assert resp.cmd("SETBIT", "b", 7, 1) == 0
        assert resp.cmd("SETBIT", "b", 7, 1) == 1  # prev bit
        assert resp.cmd("GETBIT", "b", 7) == 1
        assert resp.cmd("BITCOUNT", "b") == 1
        assert resp.cmd("BITPOS", "b", 1) == 7

    def test_hll(self, resp):
        assert resp.cmd("PFADD", "h", "a", "b", "c") == 1
        assert resp.cmd("PFCOUNT", "h") == 3
        resp.cmd("PFADD", "h2", "c", "d")
        assert resp.cmd("PFCOUNT", "h", "h2") == 4
        assert resp.cmd("PFMERGE", "h", "h2") == "OK"
        assert resp.cmd("PFCOUNT", "h") == 4

    def test_bloom_redisbloom_shape(self, resp):
        assert resp.cmd("BF.RESERVE", "bf", "0.01", "1000") == "OK"
        assert resp.cmd("BF.ADD", "bf", "x") == 1
        assert resp.cmd("BF.ADD", "bf", "x") == 0
        assert resp.cmd("BF.EXISTS", "bf", "x") == 1
        assert resp.cmd("BF.EXISTS", "bf", "ghost") == 0
        assert resp.cmd("BF.MADD", "bf", "a", "b") == [1, 1]
        assert resp.cmd("BF.MEXISTS", "bf", "a", "ghost") == [1, 0]

    def test_cms_redisbloom_shape(self, resp):
        assert resp.cmd("CMS.INITBYDIM", "c", 2048, 5) == "OK"
        assert resp.cmd("CMS.INCRBY", "c", "hot", 10) == [10]
        assert resp.cmd("CMS.QUERY", "c", "hot", "cold") == [10, 0]

    def test_lists_and_hashes(self, resp):
        assert resp.cmd("RPUSH", "l", "a", "b") == 2
        assert resp.cmd("LPUSH", "l", "z") == 3
        assert resp.cmd("LPOP", "l") == b"z"
        assert resp.cmd("RPOP", "l") == b"b"
        assert resp.cmd("LLEN", "l") == 1
        assert resp.cmd("HSET", "m", "f1", "v1", "f2", "v2") == 2
        assert resp.cmd("HGET", "m", "f1") == b"v1"
        assert resp.cmd("HDEL", "m", "f1") == 1
        assert resp.cmd("HLEN", "m") == 1

    def test_unknown_command_is_error_not_disconnect(self, resp):
        with pytest.raises(RuntimeError, match="unknown command"):
            resp.cmd("NOPE")
        assert resp.cmd("PING") == "PONG"  # connection survives

    def test_concurrent_connections(self, resp):
        import threading

        host, port = resp._sock.getpeername()

        def worker(i, results):
            c = RespClient(host, port)
            c.cmd("SET", f"cc{i}", str(i))
            results.append(c.cmd("GET", f"cc{i}"))
            c.close()

        results = []
        threads = [
            threading.Thread(target=worker, args=(i, results)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(results) == sorted(str(i).encode() for i in range(8))


class TestRespSetsZsetsCounters:
    def test_sets(self, resp):
        assert resp.cmd("SADD", "s", "a", "b", "a") == 2
        assert resp.cmd("SISMEMBER", "s", "a") == 1
        assert resp.cmd("SCARD", "s") == 2
        assert sorted(resp.cmd("SMEMBERS", "s")) == [b"a", b"b"]
        assert resp.cmd("SREM", "s", "a", "ghost") == 1

    def test_zsets(self, resp):
        assert resp.cmd("ZADD", "z", "2.5", "b", "1.0", "a") == 2
        assert resp.cmd("ZSCORE", "z", "b") == b"2.5"
        assert resp.cmd("ZRANGE", "z", 0, -1) == [b"a", b"b"]
        ws = resp.cmd("ZRANGE", "z", 0, -1, "WITHSCORES")
        # Redis formats integral scores as integers ('1', not '1.0').
        assert ws == [b"a", b"1", b"b", b"2.5"]
        assert resp.cmd("ZCARD", "z") == 2
        assert resp.cmd("ZREM", "z", "a") == 1

    def test_counters(self, resp):
        assert resp.cmd("INCR", "c") == 1
        assert resp.cmd("INCRBY", "c", 10) == 11
        assert resp.cmd("DECR", "c") == 10


class TestRespPubSub:
    def test_subscribe_publish_roundtrip(self, resp):
        import threading
        import time

        host, port = resp._sock.getpeername()
        sub = RespClient(host, port)
        frames = sub.cmd("SUBSCRIBE", "news")
        assert frames == [b"subscribe", b"news", 1]
        got = []

        def reader():
            got.append(sub._read_reply())

        t = threading.Thread(target=reader)
        t.start()
        time.sleep(0.1)
        assert resp.cmd("PUBLISH", "news", "hello") == 1
        t.join(timeout=5)
        assert got == [[b"message", b"news", b"hello"]]
        assert sub.cmd("UNSUBSCRIBE", "news") == [b"unsubscribe", b"news", 0]
        sub.close()

    def test_disconnect_drops_subscription(self, resp):
        import time

        host, port = resp._sock.getpeername()
        sub = RespClient(host, port)
        sub.cmd("SUBSCRIBE", "gone")
        sub.close()
        deadline = time.time() + 3
        while time.time() < deadline and resp.cmd("PUBLISH", "gone", "x") > 0:
            time.sleep(0.05)
        assert resp.cmd("PUBLISH", "gone", "x") == 0


class TestRespRound4:
    """MULTI/EXEC, SCAN, BLPOP/BRPOP, CMS.MERGE/INFO, BF.INFO, server
    bounds (VERDICT r3 items 6 and 10)."""

    def test_multi_exec(self, resp):
        assert resp.cmd("MULTI") == "OK"
        assert resp.cmd("SET", "ta", "1") == "QUEUED"
        assert resp.cmd("INCR", "tc") == "QUEUED"
        assert resp.cmd("INCR", "tc") == "QUEUED"
        assert resp.cmd("GET", "ta") == "QUEUED"
        out = resp.cmd("EXEC")
        assert out == ["OK", 1, 2, b"1"]
        # state really committed
        assert resp.cmd("GET", "ta") == b"1"

    def test_multi_discard(self, resp):
        resp.cmd("MULTI")
        resp.cmd("SET", "td", "x")
        assert resp.cmd("DISCARD") == "OK"
        assert resp.cmd("GET", "td") is None
        with pytest.raises(RuntimeError, match="EXEC without MULTI"):
            resp.cmd("EXEC")

    def test_multi_unknown_command_poisons(self, resp):
        resp.cmd("MULTI")
        with pytest.raises(RuntimeError, match="unknown command"):
            resp.cmd("NOSUCHCMD")
        resp.cmd("SET", "tp", "x")
        with pytest.raises(RuntimeError, match="discarded"):
            resp.cmd("EXEC")
        assert resp.cmd("GET", "tp") is None

    def test_scan_loop(self, resp):
        for i in range(25):
            resp.cmd("SET", f"scan:{i}", "v")
        seen = set()
        cursor = "0"
        while True:
            cur, keys = resp.cmd("SCAN", cursor, "MATCH", "scan:*", "COUNT", "7")
            seen.update(k.decode() for k in keys)
            cursor = cur.decode()
            if cursor == "0":
                break
        assert seen == {f"scan:{i}" for i in range(25)}

    def test_scan_survives_concurrent_deletes(self, resp):
        """The Redis SCAN guarantee: keys present for the WHOLE iteration
        are returned even when other keys are deleted mid-scan."""
        for i in range(30):
            resp.cmd("SET", f"sd:{i:02d}", "v")
        cur, keys = resp.cmd("SCAN", "0", "MATCH", "sd:*", "COUNT", "10")
        seen = {k.decode() for k in keys}
        # Delete 5 keys that sort BEFORE the cursor position.
        for k in sorted(seen)[:5]:
            resp.cmd("DEL", k)
        while cur.decode() != "0":
            cur, keys = resp.cmd(
                "SCAN", cur.decode(), "MATCH", "sd:*", "COUNT", "10"
            )
            seen.update(k.decode() for k in keys)
        # Every never-deleted key must have been returned.
        assert {f"sd:{i:02d}" for i in range(30)} <= seen
        with pytest.raises(RuntimeError, match="syntax"):
            resp.cmd("SCAN", "0", "COUNT", "0")

    def test_blpop_immediate_and_timeout(self, resp):
        resp.cmd("RPUSH", "bq", "a", "b")
        assert resp.cmd("BLPOP", "bq", "1") == [b"bq", b"a"]
        assert resp.cmd("BRPOP", "bq", "1") == [b"bq", b"b"]
        import time

        t0 = time.monotonic()
        assert resp.cmd("BLPOP", "bq", "0.3") is None
        assert 0.25 <= time.monotonic() - t0 < 3.0

    def test_blpop_blocks_until_push(self, resp):
        """A second connection pushes while the first blocks."""
        import threading

        srv_host, srv_port = resp._sock.getpeername()
        pusher = RespClient(srv_host, srv_port)
        try:
            def push_later():
                import time

                time.sleep(0.3)
                pusher.cmd("RPUSH", "bq2", "val")

            t = threading.Thread(target=push_later, daemon=True)
            t.start()
            out = resp.cmd("BLPOP", "bq2", "5")
            assert out == [b"bq2", b"val"]
            t.join(timeout=5)
        finally:
            pusher.close()

    def test_cms_merge_and_info(self, resp):
        assert resp.cmd("CMS.INITBYDIM", "c1", "1024", "4") == "OK"
        assert resp.cmd("CMS.INITBYDIM", "c2", "1024", "4") == "OK"
        resp.cmd("CMS.INCRBY", "c1", "x", "3")
        resp.cmd("CMS.INCRBY", "c2", "x", "2", "y", "5")
        assert resp.cmd("CMS.MERGE", "c1", "2", "c1", "c2") == "OK"
        assert resp.cmd("CMS.QUERY", "c1", "x") == [5]
        info = resp.cmd("CMS.INFO", "c1")
        d = dict(zip(info[::2], info[1::2]))
        assert d[b"width"] == 1024 and d[b"depth"] == 4
        assert d[b"count"] == 10  # 3 + 2 + 5 total weight

    def test_bf_info(self, resp):
        resp.cmd("BF.RESERVE", "bfi", "0.01", "1000")
        resp.cmd("BF.ADD", "bfi", "x")
        info = resp.cmd("BF.INFO", "bfi")
        d = dict(zip(info[::2], info[1::2]))
        assert d[b"Capacity"] == 1000
        assert d[b"Size"] > 0
        assert d[b"Number of filters"] == 1
        assert d[b"Number of items inserted"] >= 1


class TestRespServerBounds:
    def test_max_connections_refused(self):
        client = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
        server = RespServer(client, max_connections=2)
        conns = []
        try:
            conns = [RespClient(server.host, server.port) for _ in range(2)]
            for c in conns:
                assert c.cmd("PING") == "PONG"
            # Third connection: refused with an error, server stays up.
            import time

            time.sleep(0.1)
            refused = RespClient(server.host, server.port)
            with pytest.raises((RuntimeError, ConnectionError, OSError)):
                refused.cmd("PING")
            refused.close()
            # Existing connections unaffected; freeing one admits another.
            assert conns[0].cmd("PING") == "PONG"
            conns[0].close()
            time.sleep(0.2)
            fresh = RespClient(server.host, server.port)
            assert fresh.cmd("PING") == "PONG"
            fresh.close()
        finally:
            for c in conns[1:]:
                c.close()
            server.close()
            client.shutdown()

    def test_idle_timeout_reclaims_connection(self):
        client = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
        server = RespServer(client, idle_timeout_s=0.3)
        try:
            idle = RespClient(server.host, server.port)
            assert idle.cmd("PING") == "PONG"
            import time

            time.sleep(0.8)  # past the idle timeout
            with pytest.raises((RuntimeError, ConnectionError, OSError)):
                idle.cmd("PING")  # server closed the idle connection
            idle.close()
            # Fresh connections still served.
            fresh = RespClient(server.host, server.port)
            assert fresh.cmd("PING") == "PONG"
            fresh.close()
        finally:
            server.close()
            client.shutdown()


class TestRespReviewFixesR4:
    def test_subscribe_rejected_in_multi(self, resp):
        resp.cmd("MULTI")
        with pytest.raises(RuntimeError, match="not allowed in transactions"):
            resp.cmd("SUBSCRIBE", "ch")
        with pytest.raises(RuntimeError, match="discarded"):
            resp.cmd("EXEC")

    def test_blpop_in_multi_is_nonblocking(self, resp):
        import time

        resp.cmd("RPUSH", "mbq", "only")
        resp.cmd("MULTI")
        resp.cmd("BLPOP", "mbq", "0")
        resp.cmd("BLPOP", "mbq", "0")  # empty now: must NOT block
        t0 = time.monotonic()
        out = resp.cmd("EXEC")
        assert time.monotonic() - t0 < 2.0
        assert out[0] == [b"mbq", b"only"]
        assert out[1] is None  # nil, Redis non-blocking-in-MULTI

    def test_cms_merge_keeps_topk_config(self, resp):
        # dest created with top-K via the python API, merged via RESP.
        import redisson_tpu as _rt

        # reuse the server's embedded client through a plain CMS handle
        resp.cmd("CMS.INITBYDIM", "mk-src", "1024", "4")
        resp.cmd("CMS.INCRBY", "mk-src", "hot", "9")
        resp.cmd("CMS.INITBYDIM", "mk-dst", "1024", "4")
        resp.cmd("CMS.INCRBY", "mk-dst", "stale", "5")
        assert resp.cmd("CMS.MERGE", "mk-dst", "1", "mk-src") == "OK"
        assert resp.cmd("CMS.QUERY", "mk-dst", "hot") == [9]
        assert resp.cmd("CMS.QUERY", "mk-dst", "stale") == [0]  # overwritten


class TestTypeDumpRestore:
    def test_type_reports_redis_names(self, resp):
        assert resp.cmd("TYPE", "absent") == "none"
        resp.cmd("SET", "ts", "v")
        assert resp.cmd("TYPE", "ts") == "string"
        resp.cmd("RPUSH", "tl", "a")
        assert resp.cmd("TYPE", "tl") == "list"
        resp.cmd("HSET", "th", "f", "v")
        assert resp.cmd("TYPE", "th") == "hash"
        resp.cmd("SADD", "tset", "m")
        assert resp.cmd("TYPE", "tset") == "set"
        resp.cmd("ZADD", "tz", "1", "m")
        assert resp.cmd("TYPE", "tz") == "zset"
        resp.cmd("PFADD", "thll", "x")
        assert resp.cmd("TYPE", "thll") == "string"  # HLL is a string key
        resp.cmd("SETBIT", "tbits", "5", "1")
        assert resp.cmd("TYPE", "tbits") == "string"  # bitmaps too
        resp.cmd("BF.RESERVE", "tbf", "0.01", "1000")
        assert resp.cmd("TYPE", "tbf") == "MBbloom--"  # RedisBloom module type
        resp.cmd("CMS.INITBYDIM", "tcms", "1024", "4")
        assert resp.cmd("TYPE", "tcms") == "CMSk-TYPE"

    def test_dump_restore_string(self, resp):
        resp.cmd("SET", "dsrc", b"payload-\x00\xff")
        blob = resp.cmd("DUMP", "dsrc")
        assert blob is not None
        assert resp.cmd("RESTORE", "ddst", "0", blob) == "OK"
        assert resp.cmd("GET", "ddst") == b"payload-\x00\xff"
        # BUSYKEY without REPLACE; REPLACE overwrites.
        with pytest.raises(RuntimeError, match="BUSYKEY"):
            resp.cmd("RESTORE", "ddst", "0", blob)
        assert resp.cmd("RESTORE", "ddst", "0", blob, "REPLACE") == "OK"

    def test_dump_restore_bloom_round_trip(self, resp):
        resp.cmd("BF.RESERVE", "dbf", "0.01", "10000")
        resp.cmd("BF.MADD", "dbf", "a", "b", "c")
        blob = resp.cmd("DUMP", "dbf")
        assert blob is not None
        assert resp.cmd("RESTORE", "dbf2", "0", blob) == "OK"
        assert resp.cmd("BF.MEXISTS", "dbf2", "a", "b", "c", "zz") == [1, 1, 1, 0]

    def test_dump_restore_with_ttl(self, resp):
        resp.cmd("SET", "dttl", "v")
        blob = resp.cmd("DUMP", "dttl")
        assert resp.cmd("RESTORE", "dttl2", "60000", blob) == "OK"
        ttl = resp.cmd("TTL", "dttl2")
        assert 50 <= ttl <= 60

    def test_dump_absent_and_container_unsupported(self, resp):
        assert resp.cmd("DUMP", "never-existed") is None
        resp.cmd("RPUSH", "dlist", "x")
        with pytest.raises(RuntimeError, match="unsupported"):
            resp.cmd("DUMP", "dlist")


class TestHelloResp3:
    def test_hello_default_resp2_map_as_flat_array(self, resp):
        out = resp.cmd("HELLO")
        assert isinstance(out, list)
        d = {out[i]: out[i + 1] for i in range(0, len(out), 2)}
        assert d[b"server"] == b"redisson-tpu"
        assert d[b"proto"] == 2

    def test_hello_3_upgrades_and_pushes(self, resp):
        # Raw-socket check: HELLO 3 replies with a RESP3 map (%N) and
        # subsequent subscribe/message frames use push type '>'.
        sock = resp._sock
        resp.cmd("SET", "h3-warm", "x")  # ensure connection healthy
        sock.sendall(b"*2\r\n$5\r\nHELLO\r\n$1\r\n3\r\n")
        import time

        time.sleep(0.2)
        data = sock.recv(65536)
        assert data.startswith(b"%7\r\n"), data[:20]
        sock.sendall(b"*2\r\n$9\r\nSUBSCRIBE\r\n$3\r\nch3\r\n")
        time.sleep(0.2)
        data = sock.recv(65536)
        assert data.startswith(b">3\r\n"), data[:20]

    def test_hello_bad_version(self, resp):
        with pytest.raises(RuntimeError, match="NOPROTO"):
            resp.cmd("HELLO", "4")

    def test_hello_setname_and_auth(self, resp):
        out = resp.cmd("HELLO", "2", "SETNAME", "tester")
        assert isinstance(out, list)
        with pytest.raises(RuntimeError, match="no password"):
            resp.cmd("HELLO", "2", "AUTH", "u", "p")

    def test_restore_replace_across_stores(self, resp):
        # Redis RESTORE REPLACE deletes the old key whatever its type:
        # a sketch blob may replace a grid string, and vice versa.
        resp.cmd("BF.RESERVE", "xbf", "0.01", "1000")
        resp.cmd("BF.ADD", "xbf", "k")
        blob = resp.cmd("DUMP", "xbf")
        resp.cmd("SET", "xs", "plain")
        with pytest.raises(RuntimeError, match="BUSYKEY"):
            resp.cmd("RESTORE", "xs", "0", blob)
        assert resp.cmd("RESTORE", "xs", "0", blob, "REPLACE") == "OK"
        assert resp.cmd("TYPE", "xs") == "MBbloom--"
        # ...and back: a string payload replaces the sketch.
        sblob = b"RTPS\x00back"
        assert resp.cmd("RESTORE", "xs", "0", sblob, "REPLACE") == "OK"
        assert resp.cmd("GET", "xs") == b"back"

    def test_failed_hello3_keeps_resp2(self, resp):
        # HELLO 3 with a rejected option must NOT half-upgrade the
        # connection: subsequent pushes stay RESP2 arrays.
        with pytest.raises(RuntimeError, match="no password"):
            resp.cmd("HELLO", "3", "AUTH", "u", "p")
        sock = resp._sock
        sock.sendall(b"*2\r\n$9\r\nSUBSCRIBE\r\n$3\r\nchx\r\n")
        import time

        time.sleep(0.2)
        data = sock.recv(65536)
        assert data.startswith(b"*3\r\n"), data[:20]

    def test_error_codes(self, resp):
        # Own-code errors travel verbatim; generic ones keep ERR.
        try:
            resp.cmd("EXEC")
        except RuntimeError as e:
            assert str(e).startswith("ERR EXEC without MULTI")
        resp.cmd("SET", "ec-bk", "v")
        blob = resp.cmd("DUMP", "ec-bk")
        try:
            resp.cmd("RESTORE", "ec-bk", "0", blob)
        except RuntimeError as e:
            assert str(e).startswith("BUSYKEY"), e


class TestWidenedSurface:
    def test_string_commands(self, resp):
        assert resp.cmd("MSET", "w1", "a", "w2", "b") == "OK"
        assert resp.cmd("MGET", "w1", "w2", "nope") == [b"a", b"b", None]
        assert resp.cmd("SETNX", "w1", "x") == 0
        assert resp.cmd("SETNX", "w3", "c") == 1
        assert resp.cmd("APPEND", "w1", "ppend") == 6
        assert resp.cmd("GET", "w1") == b"append"
        assert resp.cmd("STRLEN", "w1") == 6
        assert resp.cmd("GETRANGE", "w1", "1", "3") == b"ppe"
        assert resp.cmd("GETRANGE", "w1", "-3", "-1") == b"end"
        assert resp.cmd("SETRANGE", "w1", "2", "XY") == 6
        assert resp.cmd("GET", "w1") == b"apXYnd"
        assert resp.cmd("GETSET", "w1", "new") == b"apXYnd"
        assert resp.cmd("GETDEL", "w1") == b"new"
        assert resp.cmd("EXISTS", "w1") == 0
        assert resp.cmd("SETEX", "w4", "60", "v") == "OK"
        ttl = resp.cmd("TTL", "w4")
        assert 50 <= ttl <= 60

    def test_hash_commands(self, resp):
        resp.cmd("HSET", "wh", "f1", "v1", "f2", "v2")
        got = resp.cmd("HGETALL", "wh")
        assert dict(zip(got[::2], got[1::2])) == {b"f1": b"v1", b"f2": b"v2"}
        assert resp.cmd("HMGET", "wh", "f2", "zz") == [b"v2", None]
        assert sorted(resp.cmd("HKEYS", "wh")) == [b"f1", b"f2"]
        assert sorted(resp.cmd("HVALS", "wh")) == [b"v1", b"v2"]
        assert resp.cmd("HEXISTS", "wh", "f1") == 1
        assert resp.cmd("HSETNX", "wh", "f1", "zz") == 0
        assert resp.cmd("HSETNX", "wh", "f3", "v3") == 1
        assert resp.cmd("HINCRBY", "wh", "ctr", "5") == 5
        assert resp.cmd("HINCRBY", "wh", "ctr", "-2") == 3

    def test_set_commands(self, resp):
        resp.cmd("SADD", "ws1", "a", "b", "c")
        resp.cmd("SADD", "ws2", "b", "c", "d")
        assert resp.cmd("SMISMEMBER", "ws1", "a", "d") == [1, 0]
        assert sorted(resp.cmd("SINTER", "ws1", "ws2")) == [b"b", b"c"]
        assert sorted(resp.cmd("SUNION", "ws1", "ws2")) == [b"a", b"b", b"c", b"d"]
        assert sorted(resp.cmd("SDIFF", "ws1", "ws2")) == [b"a"]
        assert resp.cmd("SMOVE", "ws1", "ws2", "a") == 1
        assert resp.cmd("SISMEMBER", "ws2", "a") == 1
        popped = resp.cmd("SPOP", "ws2")
        assert popped in (b"a", b"b", b"c", b"d")
        r = resp.cmd("SRANDMEMBER", "ws2")
        assert r is not None and resp.cmd("SISMEMBER", "ws2", r) == 1

    def test_zset_commands(self, resp):
        resp.cmd("ZADD", "wz", "1", "one", "2", "two", "3", "three")
        assert resp.cmd("ZINCRBY", "wz", "5", "one") == b"6"
        assert resp.cmd("ZRANK", "wz", "two") == 0
        assert resp.cmd("ZCOUNT", "wz", "2", "6") == 3
        assert resp.cmd("ZRANGEBYSCORE", "wz", "2", "3") == [b"two", b"three"]
        got = resp.cmd("ZRANGEBYSCORE", "wz", "2", "3", "WITHSCORES")
        assert got == [b"two", b"2", b"three", b"3"]
        assert resp.cmd("ZPOPMIN", "wz") == [b"two", b"2"]
        assert resp.cmd("ZPOPMAX", "wz") == [b"one", b"6"]

    def test_list_commands(self, resp):
        resp.cmd("RPUSH", "wl", "a", "b", "c", "d")
        assert resp.cmd("LRANGE", "wl", "0", "-1") == [b"a", b"b", b"c", b"d"]
        assert resp.cmd("LRANGE", "wl", "1", "2") == [b"b", b"c"]
        assert resp.cmd("LINDEX", "wl", "-1") == b"d"
        assert resp.cmd("LSET", "wl", "1", "B") == "OK"
        assert resp.cmd("LINDEX", "wl", "1") == b"B"
        resp.cmd("RPUSH", "wl", "B")
        assert resp.cmd("LREM", "wl", "0", "B") == 2
        assert resp.cmd("LTRIM", "wl", "1", "-1") == "OK"
        assert resp.cmd("LRANGE", "wl", "0", "-1") == [b"c", b"d"]
        assert resp.cmd("RPOPLPUSH", "wl", "wl2") == b"d"
        assert resp.cmd("LRANGE", "wl2", "0", "-1") == [b"d"]

    def test_key_admin_commands(self, resp):
        resp.cmd("SET", "wk1", "v")
        assert resp.cmd("RENAME", "wk1", "wk2") == "OK"
        assert resp.cmd("GET", "wk2") == b"v"
        resp.cmd("SET", "wk3", "x")
        assert resp.cmd("RENAMENX", "wk3", "wk2") == 0
        assert resp.cmd("RENAMENX", "wk3", "wk4") == 1
        import time

        assert resp.cmd("EXPIREAT", "wk4", str(int(time.time()) + 60)) == 1
        assert 50 <= resp.cmd("TTL", "wk4") <= 60
        assert resp.cmd("RANDOMKEY") is not None
        info = resp.cmd("INFO")
        assert b"redis_version" in info
        assert resp.cmd("CLIENT", "SETNAME", "tester") == "OK"
        assert resp.cmd("CLIENT", "GETNAME") == b"tester"
        assert resp.cmd("COMMAND") == []

    def test_topk_commands(self, resp):
        assert resp.cmd("TOPK.RESERVE", "wt", "3") == "OK"
        resp.cmd("TOPK.ADD", "wt", "a", "a", "a", "b", "b", "c")
        assert resp.cmd("TOPK.INCRBY", "wt", "d", "10") == [None]
        assert resp.cmd("TOPK.QUERY", "wt", "d", "a", "zz") == [1, 1, 0]
        assert resp.cmd("TOPK.COUNT", "wt", "d", "a", "b") == [10, 3, 2]
        assert resp.cmd("TOPK.LIST", "wt") == [b"d", b"a", b"b"]
        got = resp.cmd("TOPK.LIST", "wt", "WITHCOUNT")
        assert got == [b"d", 10, b"a", 3, b"b", 2]
        info = resp.cmd("TOPK.INFO", "wt")
        d = dict(zip(info[::2], info[1::2]))
        assert d[b"k"] == 3 and d[b"depth"] == 4

    def test_lrem_negative_count_tail_first(self, resp):
        resp.cmd("RPUSH", "wlr", "a", "x", "b", "x")
        assert resp.cmd("LREM", "wlr", "-1", "x") == 1
        assert resp.cmd("LRANGE", "wlr", "0", "-1") == [b"a", b"x", b"b"]

    def test_zcount_exclusive_bounds(self, resp):
        resp.cmd("ZADD", "wzx", "2", "two", "4", "four", "6", "six")
        assert resp.cmd("ZCOUNT", "wzx", "(2", "6") == 2
        assert resp.cmd("ZCOUNT", "wzx", "2", "(6") == 2
        assert resp.cmd("ZCOUNT", "wzx", "-inf", "+inf") == 3
        assert resp.cmd("ZRANGEBYSCORE", "wzx", "(2", "(6") == [b"four"]

    def test_zrangebyscore_limit(self, resp):
        resp.cmd("ZADD", "wzl", *[str(v) for pair in
                                  ((i, f"m{i}") for i in range(10))
                                  for v in pair])
        assert resp.cmd(
            "ZRANGEBYSCORE", "wzl", "0", "100", "LIMIT", "2", "3"
        ) == [b"m2", b"m3", b"m4"]

    def test_zpopmin_count(self, resp):
        resp.cmd("ZADD", "wzp", "1", "a", "2", "b", "3", "c")
        assert resp.cmd("ZPOPMIN", "wzp", "2") == [b"a", b"1", b"b", b"2"]
        assert resp.cmd("ZCARD", "wzp") == 1

    def test_mget_wrongtype_slot_is_nil(self, resp):
        resp.cmd("SET", "wm1", "v")
        resp.cmd("SADD", "wmset", "m")
        assert resp.cmd("MGET", "wm1", "wmset", "absent") == [b"v", None, None]

    def test_getrange_negative_end_clamps(self, resp):
        resp.cmd("SET", "wgr", "abc")
        assert resp.cmd("GETRANGE", "wgr", "0", "-4") == b"a"

    def test_pipelined_batch_with_blocking_command(self, resp):
        # Replies buffered for a pipelined batch must FLUSH before a
        # blocking command parks the connection thread — the GET's reply
        # arrives while BLPOP is still waiting.
        import time

        sock = resp._sock
        resp.cmd("SET", "pb-k", "v")
        sock.sendall(
            b"*2\r\n$3\r\nGET\r\n$4\r\npb-k\r\n"
            b"*3\r\n$5\r\nBLPOP\r\n$5\r\npb-bq\r\n$1\r\n2\r\n"
        )
        t0 = time.monotonic()
        assert resp._read_reply() == b"v"  # arrives BEFORE blpop resolves
        assert time.monotonic() - t0 < 1.5
        # feed the queue from the same test client via a second conn
        import socket as _socket

        s2 = _socket.create_connection((resp._sock.getpeername()[0],
                                        resp._sock.getpeername()[1]))
        s2.sendall(b"*3\r\n$5\r\nRPUSH\r\n$5\r\npb-bq\r\n$1\r\nz\r\n")
        out = resp._read_reply()
        assert out == [b"pb-bq", b"z"]
        s2.close()

    def test_deep_pipeline_interleaved_kinds(self, resp):
        sock = resp._sock
        n = 500
        payload = b""
        for i in range(n):
            payload += b"*3\r\n$3\r\nSET\r\n$7\r\ndp-%04d\r\n$1\r\nx\r\n" % i
            payload += b"*2\r\n$6\r\nEXISTS\r\n$7\r\ndp-%04d\r\n" % i
        sock.sendall(payload)
        for i in range(n):
            assert resp._read_reply() == "OK"
            assert resp._read_reply() == 1


    def test_pipelined_ping_then_subscribe_order(self, resp):
        # SUBSCRIBE's ack writes to the socket from its handler — the
        # batch loop must flush buffered replies first so the PING reply
        # is on the wire BEFORE the ack (reply order == command order).
        sock = resp._sock
        sock.sendall(
            b"*1\r\n$4\r\nPING\r\n"
            b"*2\r\n$9\r\nSUBSCRIBE\r\n$4\r\npbch\r\n"
        )
        assert resp._read_reply() == "PONG"
        ack = resp._read_reply()
        assert ack[0] == b"subscribe"

    def test_zrev_and_remrange(self, resp):
        resp.cmd("ZADD", "zr", "1", "a", "2", "b", "3", "c")
        assert resp.cmd("ZREVRANGE", "zr", "0", "-1") == [b"c", b"b", b"a"]
        assert resp.cmd("ZREVRANGE", "zr", "0", "1", "WITHSCORES") == [
            b"c", b"3", b"b", b"2"]
        assert resp.cmd("ZREVRANK", "zr", "c") == 0
        assert resp.cmd("ZREMRANGEBYSCORE", "zr", "2", "(3") == 1
        assert resp.cmd("ZCARD", "zr") == 2

    def test_set_store_variants(self, resp):
        resp.cmd("SADD", "ss1", "a", "b", "c")
        resp.cmd("SADD", "ss2", "b", "c", "d")
        assert resp.cmd("SINTERSTORE", "ssd", "ss1", "ss2") == 2
        assert sorted(resp.cmd("SMEMBERS", "ssd")) == [b"b", b"c"]
        assert resp.cmd("SUNIONSTORE", "ssu", "ss1", "ss2") == 4
        assert resp.cmd("SDIFFSTORE", "ssx", "ss1", "ss2") == 1
        assert resp.cmd("SMEMBERS", "ssx") == [b"a"]
        assert resp.cmd("TYPE", "ssd") == "set"

    def test_pushx_and_incrbyfloat(self, resp):
        assert resp.cmd("LPUSHX", "nolist", "x") == 0
        resp.cmd("RPUSH", "plist", "a")
        assert resp.cmd("RPUSHX", "plist", "b") == 2
        assert resp.cmd("LPUSHX", "plist", "z") == 3
        assert resp.cmd("INCRBYFLOAT", "fctr", "1.5") == b"1.5"
        assert resp.cmd("INCRBYFLOAT", "fctr", "2.5") == b"4"

    def test_numeric_int_float_interop(self, resp):
        assert resp.cmd("INCRBY", "nk", "1") == 1
        assert resp.cmd("INCRBYFLOAT", "nk", "0.5") == b"1.5"
        with pytest.raises(RuntimeError, match="not an integer"):
            resp.cmd("INCR", "nk")  # non-integral value, Redis error
        assert resp.cmd("INCRBYFLOAT", "nk", "0.5") == b"2"
        assert resp.cmd("INCR", "nk") == 3  # integral again: int ops resume

    def test_store_empty_result_deletes_dest(self, resp):
        resp.cmd("SADD", "se1", "x")
        resp.cmd("SADD", "se2", "y")
        resp.cmd("SET", "sed", "old")
        assert resp.cmd("SINTERSTORE", "sed", "se1", "se2") == 0
        assert resp.cmd("EXISTS", "sed") == 0

    def test_zrevrange_beyond_left_end(self, resp):
        resp.cmd("ZADD", "zb", "1", "a", "2", "b", "3", "c")
        assert resp.cmd("ZREVRANGE", "zb", "0", "-5") == []

    def test_protocol_error_replies_then_closes(self, resp):
        sock = resp._sock
        sock.sendall(b"*abc\r\n")
        import time

        time.sleep(0.2)
        data = sock.recv(4096)
        assert data.startswith(b"-ERR Protocol error"), data
        assert sock.recv(4096) == b""  # server closed the connection

    def test_numeric_on_string_keys_interop(self, resp):
        # Redis counters ARE string keys: SET/INCR/GET on one key.
        resp.cmd("SET", "snum", "5")
        assert resp.cmd("INCR", "snum") == 6
        assert resp.cmd("GET", "snum") == b"6"
        assert resp.cmd("TYPE", "snum") == "string"
        assert resp.cmd("INCRBYFLOAT", "snum", "0.25") == b"6.25"
        assert resp.cmd("GET", "snum") == b"6.25"
        with pytest.raises(RuntimeError, match="not an integer"):
            resp.cmd("INCR", "snum")
        # Precision: values past 2^53 keep exact int arithmetic.
        resp.cmd("SET", "big", "9007199254740993")
        assert resp.cmd("INCR", "big") == 9007199254740994

    def test_wrongtype_and_execabort_codes(self, resp):
        resp.cmd("SADD", "wtset", "m")
        try:
            resp.cmd("GET", "wtset")
            assert False, "expected WRONGTYPE"
        except RuntimeError as e:
            assert str(e).startswith("WRONGTYPE"), e
        resp.cmd("MULTI")
        try:
            resp.cmd("NOSUCHCMD")
        except RuntimeError:
            pass
        try:
            resp.cmd("EXEC")
            assert False, "expected EXECABORT"
        except RuntimeError as e:
            assert str(e).startswith("EXECABORT"), e

    def test_setrange_lset_bounds(self, resp):
        resp.cmd("SET", "srk", "hello")
        with pytest.raises(RuntimeError, match="offset is out of range"):
            resp.cmd("SETRANGE", "srk", "-1", "ZZ")
        assert resp.cmd("GET", "srk") == b"hello"  # untouched
        resp.cmd("RPUSH", "lsk", "a", "b", "c")
        with pytest.raises(RuntimeError, match="index out of range"):
            resp.cmd("LSET", "lsk", "-5", "X")
        assert resp.cmd("LRANGE", "lsk", "0", "-1") == [b"a", b"b", b"c"]

    def test_srandmember_negative_count(self, resp):
        resp.cmd("SADD", "srs", "a", "b")
        out = resp.cmd("SRANDMEMBER", "srs", "-5")
        assert len(out) == 5 and set(out) <= {b"a", b"b"}
        with pytest.raises(RuntimeError, match="out of range"):
            resp.cmd("SPOP", "srs", "-1")
        assert len(resp.cmd("SPOP", "srs", "10")) == 2  # oversized: all


class TestHandshakeAndModernCommands:
    """Round-5: handshake commands stock clients send on connect
    (SELECT/CONFIG/RESET/WAIT) + the modern command set."""

    def test_select_only_db0(self, resp):
        assert resp.cmd("SELECT", 0) == "OK"
        with pytest.raises(RuntimeError, match="out of range"):
            resp.cmd("SELECT", 3)

    def test_config_get_set(self, resp):
        rows = resp.cmd("CONFIG", "GET", "maxmemory")
        assert rows == [b"maxmemory", b"0"]
        assert resp.cmd("CONFIG", "SET", "maxmemory", "100mb") == "OK"
        assert resp.cmd("CONFIG", "GET", "maxmemory") == [b"maxmemory", b"100mb"]
        rows = resp.cmd("CONFIG", "GET", "maxmemory*")
        assert b"maxmemory-policy" in rows
        with pytest.raises(RuntimeError, match="Unknown option"):
            resp.cmd("CONFIG", "SET", "bogus-key", "1")

    def test_reset(self, resp):
        assert resp.cmd("MULTI") == "OK"
        assert resp.cmd("RESET") == "RESET"
        # MULTI state discarded: EXEC now errors
        with pytest.raises(RuntimeError, match="without MULTI"):
            resp.cmd("EXEC")

    def test_wait_standalone(self, resp):
        assert resp.cmd("WAIT", 0, 100) == 0

    def test_object_encoding(self, resp):
        resp.cmd("SET", "oe", "v")
        assert resp.cmd("OBJECT", "ENCODING", "oe") == b"embstr"
        resp.cmd("RPUSH", "ol", "a")
        assert resp.cmd("OBJECT", "ENCODING", "ol") == b"quicklist"
        assert resp.cmd("OBJECT", "REFCOUNT", "oe") == 1

    def test_getex(self, resp):
        resp.cmd("SET", "ge", "v")
        assert resp.cmd("GETEX", "ge", "EX", 100) == b"v"
        assert 0 < resp.cmd("TTL", "ge") <= 100
        assert resp.cmd("GETEX", "ge", "PERSIST") == b"v"
        assert resp.cmd("TTL", "ge") == -1
        assert resp.cmd("GETEX", "missing") is None

    def test_copy(self, resp):
        resp.cmd("SET", "c1", "v1")
        assert resp.cmd("COPY", "c1", "c2") == 1
        assert resp.cmd("GET", "c2") == b"v1"
        resp.cmd("SET", "c1", "v2")
        assert resp.cmd("GET", "c2") == b"v1"  # deep copy: no aliasing
        assert resp.cmd("COPY", "c1", "c2") == 0  # dest exists
        assert resp.cmd("COPY", "c1", "c2", "REPLACE") == 1
        assert resp.cmd("GET", "c2") == b"v2"

    def test_lmove(self, resp):
        resp.cmd("RPUSH", "lsrc", "a", "b", "c")
        assert resp.cmd("LMOVE", "lsrc", "ldst", "LEFT", "RIGHT") == b"a"
        assert resp.cmd("LMOVE", "lsrc", "ldst", "RIGHT", "LEFT") == b"c"
        assert resp.cmd("LRANGE", "ldst", 0, -1) == [b"c", b"a"]
        assert resp.cmd("LRANGE", "lsrc", 0, -1) == [b"b"]
        assert resp.cmd("LMOVE", "empty", "ldst", "LEFT", "LEFT") is None

    def test_sintercard(self, resp):
        resp.cmd("SADD", "si1", "a", "b", "c")
        resp.cmd("SADD", "si2", "b", "c", "d")
        assert resp.cmd("SINTERCARD", 2, "si1", "si2") == 2
        assert resp.cmd("SINTERCARD", 2, "si1", "si2", "LIMIT", 1) == 1

    def test_lpos(self, resp):
        resp.cmd("RPUSH", "lp", "a", "b", "c", "b", "b")
        assert resp.cmd("LPOS", "lp", "b") == 1
        assert resp.cmd("LPOS", "lp", "b", "RANK", 2) == 3
        assert resp.cmd("LPOS", "lp", "b", "RANK", -1) == 4
        assert resp.cmd("LPOS", "lp", "b", "COUNT", 0) == [1, 3, 4]
        assert resp.cmd("LPOS", "lp", "zz") is None

    def test_hrandfield_zrandmember(self, resp):
        resp.cmd("HSET", "hr", "f1", "v1", "f2", "v2")
        assert resp.cmd("HRANDFIELD", "hr") in (b"f1", b"f2")
        got = resp.cmd("HRANDFIELD", "hr", 2, "WITHVALUES")
        assert len(got) == 4
        assert len(resp.cmd("HRANDFIELD", "hr", -5)) == 5  # repeats ok
        resp.cmd("ZADD", "zr", 1, "m1", 2, "m2")
        assert resp.cmd("ZRANDMEMBER", "zr") in (b"m1", b"m2")
        got = resp.cmd("ZRANDMEMBER", "zr", 2, "WITHSCORES")
        assert len(got) == 4

    def test_lmove_wrongtype_dest_keeps_element(self, resp):
        resp.cmd("RPUSH", "lmsrc", "a")
        resp.cmd("HSET", "lmdst", "f", "v")
        with pytest.raises(RuntimeError, match="WRONGTYPE"):
            resp.cmd("LMOVE", "lmsrc", "lmdst", "LEFT", "RIGHT")
        assert resp.cmd("LRANGE", "lmsrc", 0, -1) == [b"a"]  # not lost

    def test_sintercard_negative_limit_errors(self, resp):
        resp.cmd("SADD", "snl", "a")
        with pytest.raises(RuntimeError, match="negative"):
            resp.cmd("SINTERCARD", 1, "snl", "LIMIT", -1)

    def test_config_set_multi_pair(self, resp):
        # (appendonly is no longer a free stub — it went LIVE with the
        # durability tier and refuses without a journal_dir, so the
        # multi-pair case rides two still-stubbed keys.)
        assert resp.cmd("CONFIG", "SET", "maxmemory", "1mb",
                        "timeout", "10") == "OK"
        assert resp.cmd("CONFIG", "GET", "timeout") == [b"timeout", b"10"]
        with pytest.raises(RuntimeError, match="Unknown option"):
            resp.cmd("CONFIG", "SET", "maxmemory", "2mb", "bogus", "1")
        # all-or-nothing: the valid pair before the bogus one not applied
        assert resp.cmd("CONFIG", "GET", "maxmemory") == [b"maxmemory", b"1mb"]

    def test_config_set_appendonly_refused_without_journal_dir(self, resp):
        # Durability tier (ISSUE 10): acking appendonly without a
        # journal behind it would fake durability — refused, table
        # untouched.
        with pytest.raises(RuntimeError, match="journal_dir"):
            resp.cmd("CONFIG", "SET", "appendonly", "yes")
        assert resp.cmd("CONFIG", "GET", "appendonly") == [
            b"appendonly", b"no"
        ]

    def test_getex_strict_options(self, resp):
        resp.cmd("SET", "gx", "v")
        with pytest.raises(RuntimeError, match="syntax"):
            resp.cmd("GETEX", "gx", "EX", 10, "PERSIST")
        with pytest.raises(RuntimeError, match="syntax"):
            resp.cmd("GETEX", "gx", "EX")
        with pytest.raises(RuntimeError, match="syntax"):
            resp.cmd("GETEX", "gx", "BOGUS")

    def test_object_help_and_unknown(self, resp):
        assert isinstance(resp.cmd("OBJECT", "HELP"), list)
        with pytest.raises(RuntimeError, match="Unknown OBJECT"):
            resp.cmd("OBJECT", "BOGUS", "k")

    def test_copy_same_key_errors(self, resp):
        resp.cmd("SET", "cs", "v")
        with pytest.raises(RuntimeError, match="same"):
            resp.cmd("COPY", "cs", "cs", "REPLACE")
