"""RESP front door (SURVEY.md §2.4 comm row): a raw RESP2 client drives
the engine's keyspace and sketch objects over TCP."""

import socket

import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.serve.resp import RespServer


class RespClient:
    """Minimal RESP2 client (what redis-py does on the wire)."""

    def __init__(self, host, port):
        self._sock = socket.create_connection((host, port), timeout=10)
        self._buf = b""

    def cmd(self, *args):
        out = b"*" + str(len(args)).encode() + b"\r\n"
        for a in args:
            if not isinstance(a, bytes):
                a = str(a).encode()
            out += b"$" + str(len(a)).encode() + b"\r\n" + a + b"\r\n"
        self._sock.sendall(out)
        return self._read_reply()

    def _line(self):
        while b"\r\n" not in self._buf:
            self._buf += self._sock.recv(65536)
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _exact(self, n):
        while len(self._buf) < n + 2:
            self._buf += self._sock.recv(65536)
        out, self._buf = self._buf[:n], self._buf[n + 2:]
        return out

    def _read_reply(self):
        line = self._line()
        t, body = line[:1], line[1:]
        if t == b"+":
            return body.decode()
        if t == b"-":
            raise RuntimeError(body.decode())
        if t == b":":
            return int(body)
        if t == b"$":
            n = int(body)
            return None if n < 0 else self._exact(n)
        if t == b"*":
            return [self._read_reply() for _ in range(int(body))]
        raise RuntimeError(f"bad reply type {t!r}")

    def close(self):
        self._sock.close()


@pytest.fixture
def resp():
    client = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    server = RespServer(client)
    conn = RespClient(server.host, server.port)
    yield conn
    conn.close()
    server.close()
    client.shutdown()


class TestRespFrontDoor:
    def test_ping_echo(self, resp):
        assert resp.cmd("PING") == "PONG"
        assert resp.cmd("ECHO", "hello") == b"hello"

    def test_strings_and_keys(self, resp):
        assert resp.cmd("SET", "k", "v") == "OK"
        assert resp.cmd("GET", "k") == b"v"
        assert resp.cmd("EXISTS", "k") == 1
        assert resp.cmd("DBSIZE") == 1
        assert resp.cmd("DEL", "k") == 1
        assert resp.cmd("GET", "k") is None

    def test_expire_ttl(self, resp):
        resp.cmd("SET", "e", "v", "EX", "30")
        ttl = resp.cmd("TTL", "e")
        assert 0 < ttl <= 30
        assert resp.cmd("PERSIST", "e") == 1
        assert resp.cmd("TTL", "e") == -1

    def test_bitmaps(self, resp):
        assert resp.cmd("SETBIT", "b", 7, 1) == 0
        assert resp.cmd("SETBIT", "b", 7, 1) == 1  # prev bit
        assert resp.cmd("GETBIT", "b", 7) == 1
        assert resp.cmd("BITCOUNT", "b") == 1
        assert resp.cmd("BITPOS", "b", 1) == 7

    def test_hll(self, resp):
        assert resp.cmd("PFADD", "h", "a", "b", "c") == 1
        assert resp.cmd("PFCOUNT", "h") == 3
        resp.cmd("PFADD", "h2", "c", "d")
        assert resp.cmd("PFCOUNT", "h", "h2") == 4
        assert resp.cmd("PFMERGE", "h", "h2") == "OK"
        assert resp.cmd("PFCOUNT", "h") == 4

    def test_bloom_redisbloom_shape(self, resp):
        assert resp.cmd("BF.RESERVE", "bf", "0.01", "1000") == "OK"
        assert resp.cmd("BF.ADD", "bf", "x") == 1
        assert resp.cmd("BF.ADD", "bf", "x") == 0
        assert resp.cmd("BF.EXISTS", "bf", "x") == 1
        assert resp.cmd("BF.EXISTS", "bf", "ghost") == 0
        assert resp.cmd("BF.MADD", "bf", "a", "b") == [1, 1]
        assert resp.cmd("BF.MEXISTS", "bf", "a", "ghost") == [1, 0]

    def test_cms_redisbloom_shape(self, resp):
        assert resp.cmd("CMS.INITBYDIM", "c", 2048, 5) == "OK"
        assert resp.cmd("CMS.INCRBY", "c", "hot", 10) == [10]
        assert resp.cmd("CMS.QUERY", "c", "hot", "cold") == [10, 0]

    def test_lists_and_hashes(self, resp):
        assert resp.cmd("RPUSH", "l", "a", "b") == 2
        assert resp.cmd("LPUSH", "l", "z") == 3
        assert resp.cmd("LPOP", "l") == b"z"
        assert resp.cmd("RPOP", "l") == b"b"
        assert resp.cmd("LLEN", "l") == 1
        assert resp.cmd("HSET", "m", "f1", "v1", "f2", "v2") == 2
        assert resp.cmd("HGET", "m", "f1") == b"v1"
        assert resp.cmd("HDEL", "m", "f1") == 1
        assert resp.cmd("HLEN", "m") == 1

    def test_unknown_command_is_error_not_disconnect(self, resp):
        with pytest.raises(RuntimeError, match="unknown command"):
            resp.cmd("NOPE")
        assert resp.cmd("PING") == "PONG"  # connection survives

    def test_concurrent_connections(self, resp):
        import threading

        host, port = resp._sock.getpeername()

        def worker(i, results):
            c = RespClient(host, port)
            c.cmd("SET", f"cc{i}", str(i))
            results.append(c.cmd("GET", f"cc{i}"))
            c.close()

        results = []
        threads = [
            threading.Thread(target=worker, args=(i, results)) for i in range(8)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(results) == sorted(str(i).encode() for i in range(8))


class TestRespSetsZsetsCounters:
    def test_sets(self, resp):
        assert resp.cmd("SADD", "s", "a", "b", "a") == 2
        assert resp.cmd("SISMEMBER", "s", "a") == 1
        assert resp.cmd("SCARD", "s") == 2
        assert sorted(resp.cmd("SMEMBERS", "s")) == [b"a", b"b"]
        assert resp.cmd("SREM", "s", "a", "ghost") == 1

    def test_zsets(self, resp):
        assert resp.cmd("ZADD", "z", "2.5", "b", "1.0", "a") == 2
        assert resp.cmd("ZSCORE", "z", "b") == b"2.5"
        assert resp.cmd("ZRANGE", "z", 0, -1) == [b"a", b"b"]
        ws = resp.cmd("ZRANGE", "z", 0, -1, "WITHSCORES")
        # Redis formats integral scores as integers ('1', not '1.0').
        assert ws == [b"a", b"1", b"b", b"2.5"]
        assert resp.cmd("ZCARD", "z") == 2
        assert resp.cmd("ZREM", "z", "a") == 1

    def test_counters(self, resp):
        assert resp.cmd("INCR", "c") == 1
        assert resp.cmd("INCRBY", "c", 10) == 11
        assert resp.cmd("DECR", "c") == 10


class TestRespPubSub:
    def test_subscribe_publish_roundtrip(self, resp):
        import threading
        import time

        host, port = resp._sock.getpeername()
        sub = RespClient(host, port)
        frames = sub.cmd("SUBSCRIBE", "news")
        assert frames == [b"subscribe", b"news", 1]
        got = []

        def reader():
            got.append(sub._read_reply())

        t = threading.Thread(target=reader)
        t.start()
        time.sleep(0.1)
        assert resp.cmd("PUBLISH", "news", "hello") == 1
        t.join(timeout=5)
        assert got == [[b"message", b"news", b"hello"]]
        assert sub.cmd("UNSUBSCRIBE", "news") == [b"unsubscribe", b"news", 0]
        sub.close()

    def test_disconnect_drops_subscription(self, resp):
        import time

        host, port = resp._sock.getpeername()
        sub = RespClient(host, port)
        sub.cmd("SUBSCRIBE", "gone")
        sub.close()
        deadline = time.time() + 3
        while time.time() < deadline and resp.cmd("PUBLISH", "gone", "x") > 0:
            time.sleep(0.05)
        assert resp.cmd("PUBLISH", "gone", "x") == 0
