"""RESP front door: streams, geo, and scripting families (round-5
VERDICT item 3) — a raw socket client drives consumer groups, geo
searches, and registered functions end-to-end."""

import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.serve.resp import RespServer

from test_resp_server import RespClient


@pytest.fixture
def stack():
    client = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    # Scripting: loopback bind, so enabling is permitted (the gating
    # itself is covered by tests/test_script_gating.py).
    server = RespServer(client, enable_python_scripts=True)
    conn = RespClient(server.host, server.port)
    yield client, conn
    conn.close()
    server.close()
    client.shutdown()


class TestRespStreams:
    def test_xadd_xlen_xrange(self, stack):
        _, c = stack
        id1 = c.cmd("XADD", "st", "*", "f1", "v1", "f2", "v2")
        assert b"-" in id1
        id2 = c.cmd("XADD", "st", "*", "f1", "v3")
        assert c.cmd("XLEN", "st") == 2
        rows = c.cmd("XRANGE", "st", "-", "+")
        assert rows[0][0] == id1 and rows[0][1] == [b"f1", b"v1", b"f2", b"v2"]
        assert rows[1][0] == id2
        rev = c.cmd("XREVRANGE", "st", "+", "-")
        assert [r[0] for r in rev] == [id2, id1]
        one = c.cmd("XRANGE", "st", "-", "+", "COUNT", 1)
        assert len(one) == 1

    def test_xadd_explicit_id_and_errors(self, stack):
        _, c = stack
        assert c.cmd("XADD", "st2", "5-1", "a", "1") == b"5-1"
        with pytest.raises(RuntimeError, match="equal or smaller"):
            c.cmd("XADD", "st2", "5-1", "a", "2")
        assert c.cmd("XADD", "st2", "5-2", "a", "2") == b"5-2"
        # NOMKSTREAM on a missing stream: nil, nothing created
        assert c.cmd("XADD", "nope", "NOMKSTREAM", "*", "a", "1") is None
        assert c.cmd("EXISTS", "nope") == 0

    def test_xdel_xtrim(self, stack):
        _, c = stack
        ids = [c.cmd("XADD", "st3", "*", "i", str(i)) for i in range(5)]
        assert c.cmd("XDEL", "st3", ids[0], ids[1]) == 2
        assert c.cmd("XLEN", "st3") == 3
        assert c.cmd("XTRIM", "st3", "MAXLEN", 1) == 2
        assert c.cmd("XLEN", "st3") == 1

    def test_xread(self, stack):
        _, c = stack
        id1 = c.cmd("XADD", "sr", "*", "k", "v")
        out = c.cmd("XREAD", "COUNT", 10, "STREAMS", "sr", "0-0")
        assert out == [[b"sr", [[id1, [b"k", b"v"]]]]]
        # nothing after the last id -> nil
        assert c.cmd("XREAD", "STREAMS", "sr", id1) is None

    def test_consumer_group_end_to_end(self, stack):
        """The VERDICT 'done' criterion: drive a consumer group over the
        socket — create, read-group, pending, ack, claim."""
        _, c = stack
        assert c.cmd("XGROUP", "CREATE", "jobs", "g1", "0", "MKSTREAM") == "OK"
        with pytest.raises(RuntimeError, match="BUSYGROUP"):
            c.cmd("XGROUP", "CREATE", "jobs", "g1", "0")
        id1 = c.cmd("XADD", "jobs", "*", "task", "a")
        id2 = c.cmd("XADD", "jobs", "*", "task", "b")

        out = c.cmd("XREADGROUP", "GROUP", "g1", "w1", "COUNT", 1,
                    "STREAMS", "jobs", ">")
        assert out == [[b"jobs", [[id1, [b"task", b"a"]]]]]
        out = c.cmd("XREADGROUP", "GROUP", "g1", "w2",
                    "STREAMS", "jobs", ">")
        assert out == [[b"jobs", [[id2, [b"task", b"b"]]]]]

        total, lo, hi, consumers = c.cmd("XPENDING", "jobs", "g1")
        assert total == 2 and lo == id1 and hi == id2
        assert sorted(consumers) == [[b"w1", b"1"], [b"w2", b"1"]]

        rows = c.cmd("XPENDING", "jobs", "g1", "-", "+", 10)
        assert [r[0] for r in rows] == [id1, id2]
        assert rows[0][1] == b"w1" and rows[0][3] == 1

        assert c.cmd("XACK", "jobs", "g1", id1) == 1
        assert c.cmd("XACK", "jobs", "g1", id1) == 0  # already acked
        total = c.cmd("XPENDING", "jobs", "g1")[0]
        assert total == 1

        # claim w2's entry for w1 (idle 0ms threshold)
        claimed = c.cmd("XCLAIM", "jobs", "g1", "w1", 0, id2)
        assert claimed == [[id2, [b"task", b"b"]]]
        rows = c.cmd("XPENDING", "jobs", "g1", "-", "+", 10)
        assert rows[0][1] == b"w1"

        # autoclaim sweeps the rest
        cur, entries, deleted = c.cmd(
            "XAUTOCLAIM", "jobs", "g1", "w3", 0, "0-0"
        )
        assert cur == b"0-0" and [e[0] for e in entries] == [id2]
        assert deleted == []

        assert c.cmd("XGROUP", "DESTROY", "jobs", "g1") == 1
        with pytest.raises(RuntimeError, match="NOGROUP"):
            c.cmd("XREADGROUP", "GROUP", "g1", "w1", "STREAMS", "jobs", ">")

    def test_xinfo(self, stack):
        _, c = stack
        c.cmd("XADD", "si", "7-1", "a", "1")
        c.cmd("XGROUP", "CREATE", "si", "g", "0")
        info = c.cmd("XINFO", "STREAM", "si")
        d = dict(zip(info[::2], info[1::2]))
        assert d[b"length"] == 1 and d[b"last-generated-id"] == b"7-1"
        groups = c.cmd("XINFO", "GROUPS", "si")
        assert len(groups) == 1
        g = dict(zip(groups[0][::2], groups[0][1::2]))
        assert g[b"name"] == b"g"
        c.cmd("XREADGROUP", "GROUP", "g", "w", "STREAMS", "si", ">")
        consumers = c.cmd("XINFO", "CONSUMERS", "si", "g")
        cd = dict(zip(consumers[0][::2], consumers[0][1::2]))
        assert cd[b"name"] == b"w" and cd[b"pending"] == 1

    def test_python_api_interop(self, stack):
        """Entries XADDed over the wire are visible to the Python Stream
        API and vice versa (one keyspace)."""
        client, c = stack
        c.cmd("XADD", "shared", "1-1", "src", "wire")
        s = client.get_stream("shared")
        # One keyspace: the wire entry is visible to the Python handle
        # (values decode through the handle's OWN codec, so only the
        # codec-independent surface is asserted here).
        assert s.size() == 1
        assert s.last_id() == "1-1"
        assert c.cmd("TYPE", "shared") == "stream"


class TestRespGeo:
    PALERMO = (13.361389, 38.115556)
    CATANIA = (15.087269, 37.502669)

    def _load(self, c):
        assert c.cmd("GEOADD", "Sicily",
                     str(self.PALERMO[0]), str(self.PALERMO[1]), "Palermo",
                     str(self.CATANIA[0]), str(self.CATANIA[1]), "Catania") == 2

    def test_geoadd_geopos_geodist(self, stack):
        _, c = stack
        self._load(c)
        pos = c.cmd("GEOPOS", "Sicily", "Palermo", "ghost")
        assert abs(float(pos[0][0]) - self.PALERMO[0]) < 1e-4
        assert pos[1] is None
        d_m = float(c.cmd("GEODIST", "Sicily", "Palermo", "Catania"))
        d_km = float(c.cmd("GEODIST", "Sicily", "Palermo", "Catania", "km"))
        assert 160_000 < d_m < 170_000 and abs(d_km - d_m / 1000) < 0.01
        assert c.cmd("GEODIST", "Sicily", "Palermo", "ghost") is None

    def test_geosearch_radius_and_box(self, stack):
        """The VERDICT 'done' criterion: a geo radius query over the
        socket; plus the r5 box shape."""
        _, c = stack
        self._load(c)
        out = c.cmd("GEOSEARCH", "Sicily", "FROMLONLAT", "15", "37",
                    "BYRADIUS", "200", "km", "ASC")
        assert out == [b"Catania", b"Palermo"]
        out = c.cmd("GEOSEARCH", "Sicily", "FROMMEMBER", "Palermo",
                    "BYRADIUS", "1", "km")
        assert out == [b"Palermo"]
        # BYBOX 400x400 km centered at (15,37) catches both cities
        out = c.cmd("GEOSEARCH", "Sicily", "FROMLONLAT", "15", "37",
                    "BYBOX", "400", "400", "km", "ASC", "COUNT", 10)
        assert out == [b"Catania", b"Palermo"]
        # WITH* flags
        rows = c.cmd("GEOSEARCH", "Sicily", "FROMLONLAT", "15", "37",
                     "BYRADIUS", "200", "km", "ASC",
                     "WITHCOORD", "WITHDIST", "WITHHASH")
        assert rows[0][0] == b"Catania"
        assert float(rows[0][1]) < 60  # ~56 km
        assert isinstance(rows[0][2], int)  # 52-bit hash
        assert abs(float(rows[0][3][0]) - self.CATANIA[0]) < 1e-4

    def test_geosearchstore(self, stack):
        _, c = stack
        self._load(c)
        n = c.cmd("GEOSEARCHSTORE", "dest", "Sicily",
                  "FROMLONLAT", "15", "37", "BYRADIUS", "200", "km",
                  "ASC", "STOREDIST")
        assert n == 2
        rows = c.cmd("ZRANGE", "dest", 0, -1, "WITHSCORES")
        assert rows[0] == b"Catania"
        assert float(rows[1]) < 60  # distance-as-score in km

    def test_geohash(self, stack):
        _, c = stack
        self._load(c)
        out = c.cmd("GEOHASH", "Sicily", "Palermo")
        assert out[0].startswith(b"sq")  # Palermo's well-known geohash


class TestReviewFixes:
    """Regressions for the round-5 inline-review findings on this
    surface."""

    def test_xadd_malformed_id_error(self, stack):
        _, c = stack
        with pytest.raises(RuntimeError, match="Invalid stream ID"):
            c.cmd("XADD", "stx", "notanid", "f", "v")

    def test_xreadgroup_bad_id_is_not_nogroup(self, stack):
        _, c = stack
        c.cmd("XGROUP", "CREATE", "sty", "g", "0", "MKSTREAM")
        with pytest.raises(RuntimeError, match="Invalid stream ID"):
            c.cmd("XREADGROUP", "GROUP", "g", "w", "STREAMS", "sty", "bogus!")

    def test_xautoclaim_justid(self, stack):
        _, c = stack
        c.cmd("XGROUP", "CREATE", "stz", "g", "0", "MKSTREAM")
        eid = c.cmd("XADD", "stz", "*", "f", "v")
        c.cmd("XREADGROUP", "GROUP", "g", "w1", "STREAMS", "stz", ">")
        cur, ids, deleted = c.cmd(
            "XAUTOCLAIM", "stz", "g", "w2", 0, "0-0", "JUSTID"
        )
        assert ids == [eid] and deleted == []

    def test_storedist_member_name_not_a_flag(self, stack):
        """A member literally named 'storedist' must stay a member."""
        _, c = stack
        c.cmd("GEOADD", "g52", "13.36", "38.11", "storedist")
        n = c.cmd("GEOSEARCHSTORE", "d52", "g52", "FROMMEMBER", "storedist",
                  "BYRADIUS", "5", "km")
        assert n == 1
        assert c.cmd("ZRANGE", "d52", 0, -1) == [b"storedist"]

    def test_geohash52_redis_constants(self, stack):
        """WITHHASH uses the ±85.05112878° latitude range: Palermo's
        well-known 52-bit cell id is 3479099956230698."""
        _, c = stack
        c.cmd("GEOADD", "gh", "13.361389", "38.115556", "Palermo")
        rows = c.cmd("GEOSEARCH", "gh", "FROMLONLAT", "13.36", "38.11",
                     "BYRADIUS", "10", "km", "WITHHASH")
        assert rows[0][1] == 3479099956230698

    def test_xread_block_zero_means_forever(self, stack):
        """BLOCK 0 must wait (Redis semantics), not return instantly —
        an entry added by another connection releases it."""
        import threading
        client, c = stack
        got = []

        def reader():
            got.append(c.cmd("XREAD", "BLOCK", 0, "STREAMS", "bk", "$"))

        t = threading.Thread(target=reader)
        t.start()
        t.join(0.5)
        assert t.is_alive()  # still blocked: did NOT return instantly
        c2 = RespClient(c._sock.getpeername()[0], c._sock.getpeername()[1])
        try:
            eid = c2.cmd("XADD", "bk", "*", "f", "v").decode()
        finally:
            c2.close()
        t.join(10)
        assert not t.is_alive()
        assert got[0][0][1][0][0].decode() == eid


class TestRespScripting:
    def test_eval_expression(self, stack):
        _, c = stack
        assert c.cmd("EVAL", "1 + 2", 0) == 3
        assert c.cmd("EVAL", "ARGV[0]", 0, "hello") == b"hello"
        assert c.cmd("EVAL", "KEYS[0]", 1, "k1") == b"k1"

    def test_eval_redis_call_bridge(self, stack):
        _, c = stack
        c.cmd("SET", "greeting", "world")
        out = c.cmd("EVAL", "redis.call('GET', KEYS[0])", 1, "greeting")
        assert out == b"world"
        # write through the bridge, visible outside the script
        c.cmd("EVAL",
              "redis.call('SET', KEYS[0], ARGV[0])", 1, "made", "byscript")
        assert c.cmd("GET", "made") == b"byscript"

    def test_eval_exec_form_and_types(self, stack):
        _, c = stack
        src = ("counts = [int(redis.call('INCR', k)) for k in KEYS]\n"
               "result = counts")
        assert c.cmd("EVAL", src, 2, "c1", "c2") == [1, 1]
        assert c.cmd("EVAL", "None", 0) is None
        assert c.cmd("EVAL", "True", 0) == 1
        assert c.cmd("EVAL", "[1, 'two', [3]]", 0) == [1, b"two", [3]]

    def test_script_load_evalsha(self, stack):
        client, c = stack
        sha = c.cmd("SCRIPT", "LOAD", "int(ARGV[0]) * 2")
        assert len(sha) == 40
        assert c.cmd("EVALSHA", sha, 0, "21") == 42
        assert c.cmd("SCRIPT", "EXISTS", sha, "0" * 40) == [1, 0]
        # mapped onto ScriptService: the Python API can run it too
        assert client.get_script().eval(sha.decode(), [], [b"5"]) == 10
        with pytest.raises(RuntimeError, match="NOSCRIPT"):
            c.cmd("EVALSHA", "f" * 40, 0)

    def test_function_load_fcall(self, stack):
        """The VERDICT 'done' criterion: register a function library and
        drive it over the socket."""
        client, c = stack
        lib = (
            "#!python name=mylib\n"
            "def doubled(keys, args):\n"
            "    return int(args[0]) * 2\n"
            "def getter(keys, args):\n"
            "    return redis.call('GET', keys[0])\n"
            "register_function('doubled', doubled, flags=('no-writes',))\n"
            "register_function('getter', getter)\n"
        )
        assert c.cmd("FUNCTION", "LOAD", lib) == b"mylib"
        assert c.cmd("FCALL", "doubled", 0, "21") == 42
        assert c.cmd("FCALL_RO", "doubled", 0, "3") == 6
        c.cmd("SET", "fk", "fv")
        assert c.cmd("FCALL", "getter", 1, "fk") == b"fv"
        with pytest.raises(RuntimeError, match="fcall_ro"):
            c.cmd("FCALL_RO", "getter", 1, "fk")
        # visible to the Python FunctionService too
        assert client.get_function().call("doubled", [], ["4"]) == 8

        libs = c.cmd("FUNCTION", "LIST")
        d = dict(zip(libs[0][::2], libs[0][1::2]))
        assert d[b"library_name"] == b"mylib"
        assert sorted(d[b"functions"]) == [b"doubled", b"getter"]

        assert c.cmd("FUNCTION", "DELETE", "mylib") == "OK"
        with pytest.raises(RuntimeError, match="not found|Function"):
            c.cmd("FCALL", "doubled", 0, "1")

    def test_function_load_requires_python_shebang(self, stack):
        _, c = stack
        with pytest.raises(RuntimeError, match="PYTHON"):
            c.cmd("FUNCTION", "LOAD", "#!lua name=x\nreturn 1")

    def test_eval_atomicity_against_grid(self, stack):
        """A script's multi-step read-modify-write is indivisible w.r.t.
        other connections (grid-lock atomicity contract)."""
        client, c = stack
        c.cmd("SET", "bal", "100")
        src = ("v = int(redis.call('GET', KEYS[0]))\n"
               "redis.call('SET', KEYS[0], str(v - int(ARGV[0])))\n"
               "result = v - int(ARGV[0])")
        import threading
        results = []

        def worker():
            c2 = RespClient(*_addr)
            try:
                for _ in range(25):
                    results.append(c2.cmd("EVAL", src, 1, "bal", "1"))
            finally:
                c2.close()

        _addr = (c._sock.getpeername()[0], c._sock.getpeername()[1])
        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.cmd("GET", "bal") == b"0"
        assert sorted(results) == list(range(0, 100))


class TestHighSweepFixes:
    """Regressions for the round-5 high-effort review sweep."""

    def test_xread_block_multiple_streams(self, stack):
        """BLOCK must work across >1 stream (it silently returned nil)."""
        import threading
        _, c = stack
        got = []

        def reader():
            got.append(c.cmd("XREAD", "BLOCK", 5000, "STREAMS",
                             "ms1", "ms2", "$", "$"))

        t = threading.Thread(target=reader)
        t.start()
        t.join(0.4)
        assert t.is_alive()  # parked, not instant-nil
        c2 = RespClient(c._sock.getpeername()[0], c._sock.getpeername()[1])
        try:
            c2.cmd("XADD", "ms2", "*", "f", "v")
        finally:
            c2.close()
        t.join(10)
        assert not t.is_alive()
        assert got[0][0][0] == b"ms2"

    def test_xreadgroup_noack_skips_pel(self, stack):
        _, c = stack
        c.cmd("XGROUP", "CREATE", "na", "g", "0", "MKSTREAM")
        c.cmd("XADD", "na", "*", "f", "v")
        out = c.cmd("XREADGROUP", "GROUP", "g", "w", "NOACK",
                    "STREAMS", "na", ">")
        assert out[0][0] == b"na" and len(out[0][1]) == 1
        assert c.cmd("XPENDING", "na", "g")[0] == 0  # PEL stayed empty

    def test_xreadgroup_explicit_id_empty_is_array_not_nil(self, stack):
        _, c = stack
        c.cmd("XGROUP", "CREATE", "ei", "g", "0", "MKSTREAM")
        out = c.cmd("XREADGROUP", "GROUP", "g", "w", "STREAMS", "ei", "0")
        assert out == [[b"ei", []]]  # Redis: array with empty list, not nil

    def test_xautoclaim_cursor_continues_on_truncation(self, stack):
        _, c = stack
        c.cmd("XGROUP", "CREATE", "ac", "g", "0", "MKSTREAM")
        ids = [c.cmd("XADD", "ac", "*", "i", str(i)) for i in range(5)]
        c.cmd("XREADGROUP", "GROUP", "g", "w1", "STREAMS", "ac", ">")
        cur, entries, _ = c.cmd("XAUTOCLAIM", "ac", "g", "w2", 0, "0-0",
                                "COUNT", 2)
        assert [e[0] for e in entries] == ids[:2]
        assert cur != b"0-0"  # truncated sweep: NOT the terminal cursor
        cur2, entries2, _ = c.cmd("XAUTOCLAIM", "ac", "g", "w2", 0, cur,
                                  "COUNT", 10)
        assert cur2 == b"0-0"
        assert [e[0] for e in entries2] == ids[2:]

    def test_xgroup_create_bad_id_not_busygroup(self, stack):
        _, c = stack
        c.cmd("XADD", "bg", "1-1", "f", "v")
        with pytest.raises(RuntimeError, match="Invalid stream ID"):
            c.cmd("XGROUP", "CREATE", "bg", "g", "notanid")

    def test_xclaim_missing_group_is_nogroup_code(self, stack):
        _, c = stack
        c.cmd("XADD", "ng", "1-1", "f", "v")
        with pytest.raises(RuntimeError, match="^NOGROUP"):
            c.cmd("XCLAIM", "ng", "ghostgroup", "w", 0, "1-1")
        with pytest.raises(RuntimeError, match="^NOGROUP"):
            c.cmd("XAUTOCLAIM", "ng", "ghostgroup", "w", 0, "0-0")
        with pytest.raises(RuntimeError, match="^NOGROUP"):
            c.cmd("XINFO", "CONSUMERS", "ng", "ghostgroup")

    def test_xpending_idle_filter_and_bad_count(self, stack):
        _, c = stack
        c.cmd("XGROUP", "CREATE", "pi", "g", "0", "MKSTREAM")
        c.cmd("XADD", "pi", "*", "f", "v")
        c.cmd("XREADGROUP", "GROUP", "g", "w", "STREAMS", "pi", ">")
        # IDLE larger than elapsed: filtered out
        assert c.cmd("XPENDING", "pi", "g", "IDLE", 60000, "-", "+", 10) == []
        assert len(c.cmd("XPENDING", "pi", "g", "IDLE", 0, "-", "+", 10)) == 1
        # malformed count on a LIVE group: not NOGROUP
        with pytest.raises(RuntimeError) as ei:
            c.cmd("XPENDING", "pi", "g", "-", "+", "notanum")
        assert "NOGROUP" not in str(ei.value)

    def test_eval_numkeys_validation(self, stack):
        _, c = stack
        with pytest.raises(RuntimeError, match="negative"):
            c.cmd("EVAL", "1", -1, "a")
        with pytest.raises(RuntimeError, match="greater"):
            c.cmd("EVAL", "1", 3, "a")

    def test_geoadd_nx_xx_ch(self, stack):
        _, c = stack
        assert c.cmd("GEOADD", "gf", "13.36", "38.11", "m1") == 1
        # NX: existing member untouched
        assert c.cmd("GEOADD", "gf", "NX", "15.08", "37.50", "m1") == 0
        pos = c.cmd("GEOPOS", "gf", "m1")
        assert abs(float(pos[0][0]) - 13.36) < 1e-4
        # XX: new member not created
        assert c.cmd("GEOADD", "gf", "XX", "15.08", "37.50", "m2") == 0
        assert c.cmd("GEOPOS", "gf", "m2") == [None]
        # CH counts coordinate changes
        assert c.cmd("GEOADD", "gf", "CH", "15.08", "37.50", "m1") == 1
        with pytest.raises(RuntimeError, match="not compatible"):
            c.cmd("GEOADD", "gf", "NX", "XX", "1", "1", "m3")

    def test_geosearch_nonpositive_count_errors(self, stack):
        _, c = stack
        c.cmd("GEOADD", "gc", "13.36", "38.11", "m1")
        with pytest.raises(RuntimeError, match="COUNT"):
            c.cmd("GEOSEARCH", "gc", "FROMLONLAT", "13", "38",
                  "BYRADIUS", "500", "km", "COUNT", 0)

    def test_script_flush_unregisters_python_side(self, stack):
        client, c = stack
        sha = c.cmd("SCRIPT", "LOAD", "7").decode()
        assert client.get_script().eval(sha, [], []) == 7
        c.cmd("SCRIPT", "FLUSH")
        with pytest.raises(KeyError):
            client.get_script().eval(sha, [], [])

    def test_geo_key_is_a_zset(self, stack):
        """Redis representation: geo keys ARE zsets with 52-bit cell
        scores — ZRANGE/ZSCORE work on them, GEOSEARCHSTORE destinations
        answer GEO reads."""
        _, c = stack
        c.cmd("GEOADD", "gz", "13.361389", "38.115556", "Palermo")
        assert c.cmd("TYPE", "gz") == "zset"
        assert int(float(c.cmd("ZSCORE", "gz", "Palermo"))) == 3479099956230698
        c.cmd("GEOSEARCHSTORE", "gzd", "gz", "FROMLONLAT", "13.36", "38.11",
              "BYRADIUS", "50", "km")
        # the destination answers GEO reads (it used to WRONGTYPE)
        out = c.cmd("GEOSEARCH", "gzd", "FROMLONLAT", "13.36", "38.11",
                    "BYRADIUS", "50", "km")
        assert out == [b"Palermo"]
