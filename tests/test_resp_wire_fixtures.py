"""Byte-exact RESP wire fixtures (round-5, VERDICT 'stock-client
interop evidence' row): no redis-cli/redis-py/Java client exists in this
environment, so protocol fidelity is pinned the environment-feasible
way — a committed table of (request bytes, expected reply bytes) pairs
transcribed from the Redis protocol specification, asserted byte-for-
byte against the server.  A stock client is a state machine over exactly
these byte sequences; matching them byte-exactly is what
"redis-py could drive it" reduces to.

Each fixture is the LITERAL wire traffic: requests as RESP arrays of
bulk strings (what every stock client sends), replies as the exact bytes
redis-server emits for the same commands on a fresh key space.
"""

import socket
import time

import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.serve.resp import RespServer

# (request wire bytes, expected reply wire bytes) — order matters,
# fixtures run as ONE session against one server.
FIXTURES = [
    # connection
    (b"*1\r\n$4\r\nPING\r\n", b"+PONG\r\n"),
    (b"*2\r\n$4\r\nPING\r\n$5\r\nhello\r\n", b"$5\r\nhello\r\n"),
    (b"*2\r\n$4\r\nECHO\r\n$3\r\nabc\r\n", b"$3\r\nabc\r\n"),
    # strings
    (b"*3\r\n$3\r\nSET\r\n$1\r\nk\r\n$5\r\nvalue\r\n", b"+OK\r\n"),
    (b"*2\r\n$3\r\nGET\r\n$1\r\nk\r\n", b"$5\r\nvalue\r\n"),
    (b"*2\r\n$3\r\nGET\r\n$7\r\nmissing\r\n", b"$-1\r\n"),
    (b"*2\r\n$6\r\nEXISTS\r\n$1\r\nk\r\n", b":1\r\n"),
    (b"*2\r\n$6\r\nSTRLEN\r\n$1\r\nk\r\n", b":5\r\n"),
    (b"*3\r\n$6\r\nAPPEND\r\n$1\r\nk\r\n$1\r\nx\r\n", b":6\r\n"),
    (b"*4\r\n$8\r\nGETRANGE\r\n$1\r\nk\r\n$1\r\n0\r\n$2\r\n-1\r\n",
     b"$6\r\nvaluex\r\n"),
    (b"*2\r\n$4\r\nTYPE\r\n$1\r\nk\r\n", b"+string\r\n"),
    (b"*2\r\n$3\r\nDEL\r\n$1\r\nk\r\n", b":1\r\n"),
    # counters
    (b"*2\r\n$4\r\nINCR\r\n$3\r\nctr\r\n", b":1\r\n"),
    (b"*3\r\n$6\r\nINCRBY\r\n$3\r\nctr\r\n$2\r\n41\r\n", b":42\r\n"),
    (b"*2\r\n$4\r\nDECR\r\n$3\r\nctr\r\n", b":41\r\n"),
    (b"*2\r\n$3\r\nGET\r\n$3\r\nctr\r\n", b"$2\r\n41\r\n"),
    (b"*3\r\n$11\r\nINCRBYFLOAT\r\n$3\r\nctr\r\n$3\r\n0.5\r\n",
     b"$4\r\n41.5\r\n"),
    # lists
    (b"*4\r\n$5\r\nRPUSH\r\n$1\r\nl\r\n$1\r\na\r\n$1\r\nb\r\n", b":2\r\n"),
    (b"*3\r\n$5\r\nLPUSH\r\n$1\r\nl\r\n$1\r\nz\r\n", b":3\r\n"),
    (b"*4\r\n$6\r\nLRANGE\r\n$1\r\nl\r\n$1\r\n0\r\n$2\r\n-1\r\n",
     b"*3\r\n$1\r\nz\r\n$1\r\na\r\n$1\r\nb\r\n"),
    (b"*2\r\n$4\r\nLPOP\r\n$1\r\nl\r\n", b"$1\r\nz\r\n"),
    (b"*2\r\n$4\r\nLLEN\r\n$1\r\nl\r\n", b":2\r\n"),
    # hashes
    (b"*4\r\n$4\r\nHSET\r\n$1\r\nh\r\n$2\r\nf1\r\n$2\r\nv1\r\n", b":1\r\n"),
    (b"*3\r\n$4\r\nHGET\r\n$1\r\nh\r\n$2\r\nf1\r\n", b"$2\r\nv1\r\n"),
    (b"*3\r\n$7\r\nHEXISTS\r\n$1\r\nh\r\n$2\r\nf1\r\n", b":1\r\n"),
    (b"*2\r\n$4\r\nHLEN\r\n$1\r\nh\r\n", b":1\r\n"),
    # sets
    (b"*4\r\n$4\r\nSADD\r\n$1\r\ns\r\n$1\r\na\r\n$1\r\nb\r\n", b":2\r\n"),
    (b"*3\r\n$9\r\nSISMEMBER\r\n$1\r\ns\r\n$1\r\na\r\n", b":1\r\n"),
    (b"*3\r\n$9\r\nSISMEMBER\r\n$1\r\ns\r\n$1\r\nq\r\n", b":0\r\n"),
    (b"*2\r\n$5\r\nSCARD\r\n$1\r\ns\r\n", b":2\r\n"),
    # zsets
    (b"*4\r\n$4\r\nZADD\r\n$1\r\nz\r\n$3\r\n1.5\r\n$1\r\nm\r\n", b":1\r\n"),
    (b"*3\r\n$6\r\nZSCORE\r\n$1\r\nz\r\n$1\r\nm\r\n", b"$3\r\n1.5\r\n"),
    (b"*2\r\n$5\r\nZCARD\r\n$1\r\nz\r\n", b":1\r\n"),
    # expiry
    (b"*3\r\n$3\r\nSET\r\n$2\r\nek\r\n$1\r\nv\r\n", b"+OK\r\n"),
    (b"*3\r\n$6\r\nEXPIRE\r\n$2\r\nek\r\n$3\r\n100\r\n", b":1\r\n"),
    (b"*2\r\n$7\r\nPERSIST\r\n$2\r\nek\r\n", b":1\r\n"),
    (b"*2\r\n$3\r\nTTL\r\n$2\r\nek\r\n", b":-1\r\n"),
    (b"*2\r\n$3\r\nTTL\r\n$5\r\nghost\r\n", b":-2\r\n"),
    # errors: exact Redis error codes a stock client keys on (prefix
    # assertions — the code is the contract, the text is free-form)
    (b"*3\r\n$4\r\nHSET\r\n$1\r\ns\r\n$1\r\nf\r\n", ("prefix", b"-ERR")),
    (b"*2\r\n$4\r\nLPOP\r\n$1\r\nh\r\n", ("prefix", b"-WRONGTYPE")),
    # transactions
    (b"*1\r\n$5\r\nMULTI\r\n", b"+OK\r\n"),
    (b"*3\r\n$3\r\nSET\r\n$2\r\ntk\r\n$1\r\n1\r\n", b"+QUEUED\r\n"),
    (b"*2\r\n$4\r\nINCR\r\n$2\r\ntk\r\n", b"+QUEUED\r\n"),
    (b"*1\r\n$4\r\nEXEC\r\n", b"*2\r\n+OK\r\n:2\r\n"),
    # pub/sub wire shape (subscribe ack frame)
    (b"*2\r\n$9\r\nSUBSCRIBE\r\n$2\r\nch\r\n",
     b"*3\r\n$9\r\nsubscribe\r\n$2\r\nch\r\n:1\r\n"),
]


@pytest.fixture
def server():
    client = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    srv = RespServer(client)
    yield srv
    srv.close()
    client.shutdown()


def _recv_reply(sock, want_len):
    out = b""
    deadline = time.monotonic() + 5
    while len(out) < want_len and time.monotonic() < deadline:
        try:
            data = sock.recv(65536)
        except socket.timeout:
            break
        if not data:
            break
        out += data
    return out


def _recv_line(sock):
    """One CRLF-terminated reply line; fails (never spins) on close."""
    got = b""
    while not got.endswith(b"\r\n"):
        data = sock.recv(65536)
        if not data:
            raise ConnectionError(f"connection closed mid-reply: {got!r}")
        got += data
    return got


def test_wire_fixtures_byte_exact(server):
    s = socket.create_connection((server.host, server.port), timeout=3)
    s.settimeout(2)
    try:
        for req, want in FIXTURES:
            s.sendall(req)
            if isinstance(want, tuple):  # ("prefix", b"-CODE")
                got = _recv_line(s)
                assert got.startswith(want[1]), (req, got)
                continue
            got = _recv_reply(s, len(want))
            assert got == want, (req, got, want)
    finally:
        s.close()


def test_inline_command_fixture(server):
    """redis-cli's fallback inline protocol (no RESP framing)."""
    s = socket.create_connection((server.host, server.port), timeout=3)
    s.settimeout(2)
    try:
        s.sendall(b"PING\r\n")
        assert _recv_reply(s, 7) == b"+PONG\r\n"
        s.sendall(b"SET ik iv\r\n")
        assert _recv_reply(s, 5) == b"+OK\r\n"
        s.sendall(b"GET ik\r\n")
        assert _recv_reply(s, 8) == b"$2\r\niv\r\n"
    finally:
        s.close()


def test_pipelined_fixture_single_write(server):
    """A stock client's pipeline: N requests in one write, N replies in
    order — byte-exact concatenation."""
    s = socket.create_connection((server.host, server.port), timeout=3)
    s.settimeout(2)
    try:
        s.sendall(
            b"*3\r\n$3\r\nSET\r\n$1\r\np\r\n$1\r\n1\r\n"
            b"*2\r\n$4\r\nINCR\r\n$1\r\np\r\n"
            b"*2\r\n$3\r\nGET\r\n$1\r\np\r\n"
        )
        want = b"+OK\r\n:2\r\n$1\r\n2\r\n"
        assert _recv_reply(s, len(want)) == want
    finally:
        s.close()
