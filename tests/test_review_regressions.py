"""Regression tests for review findings (round 1): wrongtype guards,
rename safety, bitop sizing, dump parity, bitpos edge, top-K via add()."""

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config


@pytest.fixture(params=["tpu", "host"])
def client(request):
    cfg = Config()
    if request.param == "tpu":
        cfg.use_tpu_sketch(min_bucket=64)
    return redisson_tpu.create(cfg)


def test_rename_missing_source_keeps_destination(client):
    bf = client.get_bloom_filter("dest")
    bf.try_init(100, 0.01)
    bf.add("v")
    assert client._engine.rename("nonexistent", "dest") is False
    assert bf.contains("v")  # destination untouched
    assert client._engine.rename("dest", "dest") is False
    assert bf.contains("v")


def test_wrongtype_guards(client):
    bf = client.get_bloom_filter("typed")
    bf.try_init(100, 0.01)
    with pytest.raises(TypeError):
        client.get_hyper_log_log("typed").add("x")
    with pytest.raises(TypeError):
        client.get_hyper_log_log("typed").count()
    with pytest.raises(TypeError):
        client.get_bit_set("typed").set(1)
    with pytest.raises(TypeError):
        client.get_bit_set("typed").cardinality()
    with pytest.raises(TypeError):
        client.get_count_min_sketch("typed").try_init(2, 64)
    h = client.get_hyper_log_log("reallyhll")
    h.add("x")
    with pytest.raises(TypeError):
        h.count_with("typed")


def test_bitop_with_larger_destination(client):
    big = client.get_bit_set("bigdst")
    big.set(5000)  # larger size class than the sources
    big.clear_bit(5000)
    a = client.get_bit_set("srcA")
    b = client.get_bit_set("srcB")
    a.set_many(np.array([1, 2]))
    b.set_many(np.array([2, 3]))
    client._engine.bitset_bitop("bigdst", ("srcA", "srcB"), "or")
    arr = big.as_bit_array()
    assert sorted(np.nonzero(arr)[0].tolist()) == [1, 2, 3]


def test_to_byte_array_parity_between_modes():
    dumps = {}
    for mode in ("tpu", "host"):
        cfg = Config()
        if mode == "tpu":
            cfg.use_tpu_sketch(min_bucket=64)
        cl = redisson_tpu.create(cfg)
        bs = cl.get_bit_set("dump")
        bs.set(0)
        bs.set(77)
        dumps[mode] = bs.to_byte_array()
    assert dumps["tpu"] == dumps["host"]
    assert len(dumps["tpu"]) == 10  # ceil(78/8)


def test_first_clear_bit_all_set_parity(client):
    bs = client.get_bit_set("full")
    bs.set_range(0, 1024)  # exactly fills the smallest size class
    assert bs.first_clear_bit() == 1024


def test_cms_single_add_feeds_topk(client):
    c = client.get_count_min_sketch("cmstrk")
    c.try_init(4, 1 << 10, track_top_k=3)
    for _ in range(5):
        c.add("solo")
    top = c.top_k(1)
    assert top and top[0] == ("solo", 5)
