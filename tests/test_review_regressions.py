"""Regression tests for review findings (round 1): wrongtype guards,
rename safety, bitop sizing, dump parity, bitpos edge, top-K via add()."""

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config


@pytest.fixture(params=["tpu", "host"])
def client(request):
    cfg = Config()
    if request.param == "tpu":
        cfg.use_tpu_sketch(min_bucket=64)
    return redisson_tpu.create(cfg)


def test_rename_missing_source_keeps_destination(client):
    bf = client.get_bloom_filter("dest")
    bf.try_init(100, 0.01)
    bf.add("v")
    assert client._engine.rename("nonexistent", "dest") is False
    assert bf.contains("v")  # destination untouched
    assert client._engine.rename("dest", "dest") is False
    assert bf.contains("v")


def test_wrongtype_guards(client):
    bf = client.get_bloom_filter("typed")
    bf.try_init(100, 0.01)
    with pytest.raises(TypeError):
        client.get_hyper_log_log("typed").add("x")
    with pytest.raises(TypeError):
        client.get_hyper_log_log("typed").count()
    with pytest.raises(TypeError):
        client.get_bit_set("typed").set(1)
    with pytest.raises(TypeError):
        client.get_bit_set("typed").cardinality()
    with pytest.raises(TypeError):
        client.get_count_min_sketch("typed").try_init(2, 64)
    h = client.get_hyper_log_log("reallyhll")
    h.add("x")
    with pytest.raises(TypeError):
        h.count_with("typed")


def test_bitop_with_larger_destination(client):
    big = client.get_bit_set("bigdst")
    big.set(5000)  # larger size class than the sources
    big.clear_bit(5000)
    a = client.get_bit_set("srcA")
    b = client.get_bit_set("srcB")
    a.set_many(np.array([1, 2]))
    b.set_many(np.array([2, 3]))
    client._engine.bitset_bitop("bigdst", ("srcA", "srcB"), "or")
    arr = big.as_bit_array()
    assert sorted(np.nonzero(arr)[0].tolist()) == [1, 2, 3]


def test_to_byte_array_parity_between_modes():
    dumps = {}
    for mode in ("tpu", "host"):
        cfg = Config()
        if mode == "tpu":
            cfg.use_tpu_sketch(min_bucket=64)
        cl = redisson_tpu.create(cfg)
        bs = cl.get_bit_set("dump")
        bs.set(0)
        bs.set(77)
        dumps[mode] = bs.to_byte_array()
    assert dumps["tpu"] == dumps["host"]
    assert len(dumps["tpu"]) == 10  # ceil(78/8)


def test_first_clear_bit_all_set_parity(client):
    bs = client.get_bit_set("full")
    bs.set_range(0, 1024)  # exactly fills the smallest size class
    assert bs.first_clear_bit() == 1024


def test_cms_single_add_feeds_topk(client):
    c = client.get_count_min_sketch("cmstrk")
    c.try_init(4, 1 << 10, track_top_k=3)
    for _ in range(5):
        c.add("solo")
    top = c.top_k(1)
    assert top and top[0] == ("solo", 5)


def test_bitop_not_masks_to_logical_length(client):
    """ADVICE r1: NOT must complement the source's byte-aligned string
    (Redis BITOP NOT semantics) in BOTH engines — never the whole
    physical size-class row."""
    src = client.get_bit_set("notsrc")
    src.set_many(np.array([1, 3, 5]))  # logical length 6 -> 1-byte string
    dst = client.get_bit_set("notdst")
    client._engine.bitset_bitop("notdst", ("notsrc",), "not")
    assert dst.cardinality() == 5  # bits 0, 2, 4 + padding bits 6, 7
    arr = dst.as_bit_array()
    assert sorted(np.nonzero(arr)[0].tolist()) == [0, 2, 4, 6, 7]


def test_bitop_not_parity_between_modes():
    dumps = {}
    for mode in ("tpu", "host"):
        cfg = Config()
        if mode == "tpu":
            cfg.use_tpu_sketch(min_bucket=64)
        cl = redisson_tpu.create(cfg)
        src = cl.get_bit_set("nsrc")
        src.set_many(np.array([0, 9]))
        cl._engine.bitset_bitop("ndst", ("nsrc",), "not")
        dumps[mode] = (
            cl.get_bit_set("ndst").to_byte_array(),
            cl.get_bit_set("ndst").cardinality(),
        )
    assert dumps["tpu"] == dumps["host"]
    assert dumps["tpu"][1] == 14  # 10 logical bits -> 16-bit string, 2 set in src


def test_bitop_overwrites_destination(client):
    """ADVICE r1: Redis BITOP replaces dest entirely — stale high bits of
    a previously-larger dest must not survive."""
    dst = client.get_bit_set("owdst")
    dst.set(5000)  # dest has a high bit + large physical row
    a = client.get_bit_set("owA")
    b = client.get_bit_set("owB")
    a.set_many(np.array([1, 2]))
    b.set_many(np.array([2, 3]))
    client._engine.bitset_bitop("owdst", ("owA", "owB"), "or")
    arr = dst.as_bit_array()
    assert sorted(np.nonzero(arr)[0].tolist()) == [1, 2, 3]
    assert dst.cardinality() == 3


def test_bitop_does_not_inflate_source_logical_length(client):
    a = client.get_bit_set("lenA")
    b = client.get_bit_set("lenB")
    a.set(2)       # logical length 3
    b.set(9000)    # much larger class
    client._engine.bitset_bitop("lenD", ("lenA", "lenB"), "or")
    # Source A keeps its own logical length (3 bits -> 1-byte string):
    # NOT of it has 7 bits set, not thousands from B's size class.
    client._engine.bitset_bitop("lenNA", ("lenA",), "not")
    assert client.get_bit_set("lenNA").cardinality() == 7


def test_cms_counts_wrap_identically_between_modes():
    """ADVICE r1: CMS counters are uint32 in both engines; totals wrap
    mod 2**32 identically instead of silently diverging."""
    ests = {}
    for mode in ("tpu", "host"):
        cfg = Config()
        if mode == "tpu":
            cfg.use_tpu_sketch(min_bucket=64)
        cl = redisson_tpu.create(cfg)
        c = cl.get_count_min_sketch("wrapcms")
        c.try_init(3, 1 << 8)
        big = (1 << 31) + 7
        c.add("k", count=big)
        c.add("k", count=big)  # 2*(2^31+7) wraps to 14 mod 2^32
        ests[mode] = int(c.estimate("k"))
    assert ests["tpu"] == ests["host"] == 14


def test_fast_add_drains_pending_coalesced_reads():
    """ADVICE r1: with exact_add_semantics=False + coalescing on, a fast
    add must not overtake an earlier queued contains."""
    cfg = Config().use_tpu_sketch(
        exact_add_semantics=False, coalesce=True,
        batch_window_us=200_000, min_bucket=64,
    )
    cl = redisson_tpu.create(cfg)
    bf = cl.get_bloom_filter("orderbf")
    bf.try_init(1000, 0.01)
    # Queue a contains (sits in the window), then fast-add the same key.
    fut = bf.contains_async("late-key")
    bf.add("late-key")
    # The earlier read must NOT observe the later write.
    assert not np.any(fut.result())
    assert bf.contains("late-key") is True
    cl.shutdown()


class TestRound3AdviceFixes:
    """ADVICE r2: one logical keyspace, read-only lock paths, SET XX TTL,
    lock owner identity."""

    def _client(self):
        import redisson_tpu
        from redisson_tpu import Config

        return redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))

    def test_cross_backend_wrongtype_grid_then_sketch(self):
        import pytest

        c = self._client()
        try:
            c.get_bucket("shared-name").set("v")
            with pytest.raises(TypeError, match="WRONGTYPE|held by"):
                c.get_bloom_filter("shared-name").try_init(1000, 0.01)
        finally:
            c.shutdown()

    def test_cross_backend_wrongtype_sketch_then_grid(self):
        import pytest

        c = self._client()
        try:
            bf = c.get_bloom_filter("shared-name2")
            bf.try_init(1000, 0.01)
            with pytest.raises(TypeError, match="WRONGTYPE|held by"):
                c.get_bucket("shared-name2").set("v")
        finally:
            c.shutdown()

    def test_readonly_lock_queries_do_not_materialize(self):
        c = self._client()
        try:
            assert not c.get_lock("ro-lock").is_locked()
            assert c.get_lock("ro-lock").get_hold_count() == 0
            assert c.get_semaphore("ro-sem").available_permits() == 0
            assert c.get_count_down_latch("ro-latch").get_count() == 0
            assert c.get_rate_limiter("ro-rl").available_permits() == 0
            names = c.get_keys().get_keys()
            for n in ("ro-lock", "ro-sem", "ro-latch", "ro-rl"):
                assert n not in names, n
        finally:
            c.shutdown()

    def test_set_if_exists_clears_ttl(self):
        import time

        c = self._client()
        try:
            b = c.get_bucket("xx-ttl")
            b.set("v1", ttl_seconds=30.0)
            assert b.remain_time_to_live() > 0
            assert b.set_if_exists("v2")
            # SET XX without KEEPTTL clears the TTL, like set().
            assert b.remain_time_to_live() == -1
            assert b.get() == "v2"
        finally:
            c.shutdown()

    def test_lock_owner_uses_client_uuid(self):
        c1 = self._client()
        c2 = self._client()
        try:
            assert c1.id != c2.id
            lk = c1.get_lock("uuid-lock")
            lk.lock()
            assert lk._me()[0] == c1.id
            lk.unlock()
        finally:
            c1.shutdown()
            c2.shutdown()

    def test_cross_backend_guard_no_deadlock(self):
        """r3 review: foreign-exists probes must be lock-free — a locking
        probe deadlocks AB-BA when both backends create concurrently."""
        import threading

        import redisson_tpu
        from redisson_tpu import Config

        c = redisson_tpu.create(Config())  # host engine (default config)
        try:
            stop = threading.Event()

            def sketch_side():
                i = 0
                while not stop.is_set() and i < 300:
                    c.get_bloom_filter(f"dl-bf-{i}").try_init(100, 0.01)
                    i += 1

            def grid_side():
                i = 0
                while not stop.is_set() and i < 300:
                    c.get_bucket(f"dl-bk-{i}").set(i)
                    i += 1

            t1 = threading.Thread(target=sketch_side, daemon=True)
            t2 = threading.Thread(target=grid_side, daemon=True)
            t1.start(); t2.start()
            t1.join(timeout=10); t2.join(timeout=10)
            alive = t1.is_alive() or t2.is_alive()
            stop.set()
            assert not alive, "cross-backend creation deadlocked"
        finally:
            c.shutdown()

    def test_restore_cannot_shadow_grid(self):
        import pytest

        c = self._client()
        try:
            bf = c.get_bloom_filter("shadow-src")
            bf.try_init(100, 0.01)
            blob = bf.dump()
            c.get_bucket("shadow-dst").set("v")
            with pytest.raises(TypeError, match="WRONGTYPE|held by"):
                c._engine.restore("shadow-dst", blob)
            with pytest.raises(TypeError, match="WRONGTYPE|held by"):
                c._engine.rename("shadow-src", "shadow-dst")
        finally:
            c.shutdown()


def test_concurrent_bitset_grow_no_double_free():
    """Two threads growing the same bitset concurrently: exactly one
    migration wins, data survives, and no pool row is double-freed
    (a duplicate free hands one device row to two future tenants)."""
    import threading

    import numpy as np

    import redisson_tpu
    from redisson_tpu import Config

    c = redisson_tpu.create(
        Config().use_tpu_sketch(min_bucket=64, coalesce=False)
    )
    try:
        for round_ in range(6):
            name = f"growrace{round_}"
            bs = c.get_bit_set(name)
            bs.set_many(np.arange(0, 1024, 3, dtype=np.uint32))
            barrier = threading.Barrier(2)
            errs = []

            def grower(hi):
                try:
                    barrier.wait(5)
                    c.get_bit_set(name).set(hi)  # forces a size-class grow
                except Exception as e:  # pragma: no cover
                    errs.append(e)

            t1 = threading.Thread(target=grower, args=(200_000,))
            t2 = threading.Thread(target=grower, args=(250_000,))
            t1.start(); t2.start(); t1.join(10); t2.join(10)
            assert not errs, errs
            got = c.get_bit_set(name)
            assert bool(np.all(got.get_many(
                np.arange(0, 1024, 3, dtype=np.uint32)
            ))), "pre-grow bits lost in concurrent migration"
            assert got.get(200_000) and got.get(250_000)
            # No pool free-list may contain duplicates (double-free).
            for pool in c._engine.registry.pools():
                assert len(pool._free) == len(set(pool._free)), (
                    "double-freed row in pool free list"
                )
    finally:
        c.shutdown()


def test_host_restore_rejects_kind_model_mismatch():
    import redisson_tpu
    from redisson_tpu import Config

    c = redisson_tpu.create(Config())
    try:
        cms = c.get_count_min_sketch("kmm")
        cms.try_init(4, 1 << 10)
        cms.add(1)
        import json as _json
        import struct as _struct

        raw = cms.dump()
        (hlen,) = _struct.unpack("<I", raw[4:8])
        hdr = _json.loads(raw[8 : 8 + hlen].decode())
        assert hdr["kind"] == "cms"
        hdr["kind"] = "bloom"  # forged: kind disagrees with model_cls
        nh = _json.dumps(hdr).encode()
        forged = raw[:4] + _struct.pack("<I", len(nh)) + nh + raw[8 + hlen :]
        import pytest as _pytest

        with _pytest.raises(ValueError, match="does not match"):
            c._engine.restore("kmm2", forged)
    finally:
        c.shutdown()
