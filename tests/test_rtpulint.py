"""rtpulint (ISSUE 8): fixture-driven rule coverage + the tree gate.

Each RT rule must fire on every ``# rtpulint-expect: RTnnn`` marker in
its known-bad fixture (exact line + rule match, nothing extra) and
stay silent on the known-good fixture.  The tree gate asserts the
shipping package itself lints clean — the same check CI runs via
``python -m redisson_tpu.analysis redisson_tpu/``.
"""

import os
import re
import subprocess
import sys

import pytest

from redisson_tpu.analysis import RULES, lint_file, lint_paths, lint_source

FIXDIR = os.path.join(os.path.dirname(__file__), "fixtures", "rtpulint")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXPECT_RE = re.compile(r"#\s*rtpulint-expect:\s*(RT\d{3})")

CHECKED_RULES = ("RT001", "RT002", "RT003", "RT004", "RT005", "RT006",
                 "RT007", "RT008", "RT009", "RT011", "RT012", "RT013",
                 "RT014", "RT015")


def _expected(path):
    out = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            for m in _EXPECT_RE.finditer(line):
                out.append((i, m.group(1)))
    return sorted(out)


@pytest.mark.parametrize("rule", CHECKED_RULES)
def test_bad_corpus_fires_exactly(rule):
    path = os.path.join(FIXDIR, f"{rule.lower()}_bad.py")
    expected = _expected(path)
    assert expected, f"fixture {path} has no expect markers"
    got = sorted(
        (v.line, v.rule) for v in lint_file(path) if not v.suppressed
    )
    assert got == expected


@pytest.mark.parametrize("rule", CHECKED_RULES)
def test_good_corpus_stays_silent(rule):
    path = os.path.join(FIXDIR, f"{rule.lower()}_good.py")
    live = [v for v in lint_file(path) if not v.suppressed]
    assert live == [], [v.format() for v in live]


def test_suppression_without_reason_is_reported():
    src = (
        "# rtpulint: role=dispatch\n"
        "import time\n"
        "import threading\n"
        "_lock = threading.Lock()\n"
        "def f():\n"
        "    with _lock:\n"
        "        time.sleep(1)  # rtpulint: disable=RT001\n"
    )
    vs = lint_source(src, rel="frag.py")
    rules = sorted(v.rule for v in vs if not v.suppressed)
    # The bare disable does NOT suppress (RT001 still fires) and is
    # itself flagged (RT000).
    assert rules == ["RT000", "RT001"]


def test_suppression_unknown_rule_is_reported():
    src = "x = 1  # rtpulint: disable=RT999 because reasons\n"
    vs = lint_source(src, rel="frag.py")
    assert [v.rule for v in vs] == ["RT000"]


def test_comment_line_above_suppresses_next_line():
    src = (
        "# rtpulint: role=dispatch\n"
        "import time\n"
        "import threading\n"
        "_lock = threading.Lock()\n"
        "def f():\n"
        "    with _lock:\n"
        "        # rtpulint: disable=RT001 fixture reason\n"
        "        time.sleep(1)\n"
    )
    vs = lint_source(src, rel="frag.py")
    assert [v.rule for v in vs if not v.suppressed] == []
    assert [v.rule for v in vs if v.suppressed] == ["RT001"]


def test_tree_gate_zero_unsuppressed_violations():
    """The acceptance criterion: the shipping package lints clean (any
    deliberate violation carries an inline reasoned suppression)."""
    vs = lint_paths([os.path.join(REPO, "redisson_tpu")])
    live = [v for v in vs if not v.suppressed]
    assert live == [], "\n".join(v.format() for v in live)
    # Every RT rule has at least been exercised by the tree or the
    # suppressions (sanity: the role scoping didn't silently disable a
    # rule everywhere).
    assert {v.rule for v in vs} <= set(RULES)


def test_cli_entry_point_exits_zero_on_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "redisson_tpu.analysis", "redisson_tpu"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_flags_violations_nonzero(tmp_path):
    bad = tmp_path / "frag.py"
    bad.write_text(
        "_CACHE: dict = {}\n\n"
        "def put(name, v):\n"
        "    _CACHE[name] = v\n"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "redisson_tpu.analysis", str(bad)],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1
    assert "RT006" in proc.stdout


# -- reactor front door coverage (ISSUE 11 satellite) --------------------------


class TestReactorModuleCoverage:
    """serve/reactor.py is in RT001/RT002 scope (role `serve` resolves
    from the path), and the shipped module lints clean."""

    def test_rt001_applies_at_reactor_path(self):
        src = (
            "import threading\n"
            "lock = threading.Lock()\n"
            "def flush(sock, frame):\n"
            "    with lock:\n"
            "        sock.sendall(frame)\n"
        )
        got = lint_source(src, rel="redisson_tpu/serve/reactor.py")
        assert any(v.rule == "RT001" for v in got)

    def test_rt002_applies_at_reactor_path(self):
        src = (
            "class C:\n"
            "    def poke(self):\n"
            "        self.sock.settimeout(1.0)\n"
        )
        got = lint_source(src, rel="redisson_tpu/serve/reactor.py")
        assert any(v.rule == "RT002" for v in got)

    def test_shipped_reactor_module_lints_clean(self):
        import redisson_tpu.serve.reactor as rx

        live = [v for v in lint_file(rx.__file__) if not v.suppressed]
        assert live == [], [v.format() for v in live]


# -- suppression audit + parallel jobs (ISSUE 15 satellites) -------------------


class TestSuppressionAudit:
    """``--audit-suppressions``: a disable comment whose rule no longer
    fires at its target line is STALE (dead armor), and CI fails on it."""

    STALE_SRC = (
        "# rtpulint: role=dispatch\n"
        "import threading\n"
        "_lock = threading.Lock()\n"
        "def f():\n"
        "    with _lock:\n"
        "        # rtpulint: disable=RT001 the blocking call was removed long ago\n"
        "        x = 1\n"
    )
    LIVE_SRC = (
        "# rtpulint: role=dispatch\n"
        "import time\n"
        "import threading\n"
        "_lock = threading.Lock()\n"
        "def f():\n"
        "    with _lock:\n"
        "        # rtpulint: disable=RT001 fixture reason\n"
        "        time.sleep(1)\n"
    )

    def test_stale_suppression_reported(self, tmp_path):
        from redisson_tpu.analysis.rtpulint import audit_paths

        p = tmp_path / "frag.py"
        p.write_text(self.STALE_SRC)
        stale = audit_paths([str(p)])
        assert [(s.line, s.rules) for s in stale] == [(6, ("RT001",))]
        assert "removed long ago" in stale[0].format()

    def test_live_suppression_not_stale(self, tmp_path):
        from redisson_tpu.analysis.rtpulint import audit_paths

        p = tmp_path / "frag.py"
        p.write_text(self.LIVE_SRC)
        assert audit_paths([str(p)]) == []

    def test_rt010_comments_skipped_without_tree_pass(self, tmp_path):
        # RT010-naming comments verify against the lock graph's
        # consumed-site set; without it the audit must not guess.
        from redisson_tpu.analysis.rtpulint import audit_paths

        p = tmp_path / "frag.py"
        p.write_text("x = 1  # rtpulint: disable=RT010 ordered via catalog\n")
        assert audit_paths([str(p)]) == []
        # With an (empty) consumed-site set the same comment IS stale.
        stale = audit_paths([str(p)], rt010_sites=set())
        assert [s.rules for s in stale] == [("RT010",)]

    def test_cli_audit_fails_on_stale(self, tmp_path):
        bad = tmp_path / "frag.py"
        bad.write_text(self.STALE_SRC)
        proc = subprocess.run(
            [sys.executable, "-m", "redisson_tpu.analysis",
             str(bad), "--audit-suppressions"],
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 1
        assert "stale suppression" in proc.stdout
        assert "audit: 1 stale" in proc.stderr

    def test_cli_audit_passes_on_tree(self):
        """Acceptance: every reasoned suppression in the shipping
        package still suppresses a live finding."""
        proc = subprocess.run(
            [sys.executable, "-m", "redisson_tpu.analysis",
             "redisson_tpu", "--audit-suppressions"],
            cwd=REPO, capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "audit: 0 stale" in proc.stderr


class TestParallelJobs:
    """``--jobs N``: per-file analysis fans out to N processes with
    findings byte-identical to the serial pass."""

    def test_lint_paths_jobs_byte_identical(self):
        serial = lint_paths([FIXDIR], jobs=1)
        parallel = lint_paths([FIXDIR], jobs=4)
        fmt = lambda vs: [(v.format(), v.suppressed) for v in vs]
        assert fmt(parallel) == fmt(serial)
        assert serial, "fixture corpus produced no findings at all"

    def test_audit_paths_jobs_byte_identical(self, tmp_path):
        from redisson_tpu.analysis.rtpulint import audit_paths

        for i in range(6):
            p = tmp_path / f"frag{i}.py"
            p.write_text(TestSuppressionAudit.STALE_SRC)
        serial = audit_paths([str(tmp_path)], jobs=1)
        parallel = audit_paths([str(tmp_path)], jobs=3)
        fmt = lambda ss: [s.format() for s in ss]
        assert fmt(parallel) == fmt(serial)
        assert len(serial) == 6

    def test_cli_jobs_output_identical(self, tmp_path):
        def run(jobs):
            return subprocess.run(
                [sys.executable, "-m", "redisson_tpu.analysis",
                 FIXDIR, "--jobs", jobs, "--show-suppressed"],
                cwd=REPO, capture_output=True, text=True, timeout=300,
            )
        one, four = run("1"), run("4")
        assert one.returncode == four.returncode == 1
        assert one.stdout == four.stdout


class TestRT015Catalog:
    """The linter's literal kind mirror must track obs/events.py KINDS
    exactly (both directions): a kind added to the catalog without the
    mirror would lint-fail its own emit site, a kind added to the
    mirror alone would let an unregistered emit through to a runtime
    ValueError."""

    def test_mirror_equals_catalog_both_ways(self):
        from redisson_tpu.analysis.rtpulint import _RT015_KINDS
        from redisson_tpu.obs.events import KINDS

        assert set(_RT015_KINDS) == set(KINDS), (
            "obs/events.py KINDS and rtpulint._RT015_KINDS drifted: "
            f"catalog-only={sorted(set(KINDS) - set(_RT015_KINDS))} "
            f"mirror-only={sorted(set(_RT015_KINDS) - set(KINDS))}"
        )
