"""Run-length segment metadata path (round 4, PROFILE.md lever 1):
per-chunk row/m/is_add ship once per run and expand on device.  These
tests pin equivalence with the per-op-array path and the golden engine."""

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.codecs import LongCodec


def _client(**kw):
    kw.setdefault("batch_window_us", 500)
    cfg = Config().set_codec(LongCodec()).use_tpu_sketch(
        coalesce=True, exact_add_semantics=True, min_bucket=64, **kw
    )
    return redisson_tpu.create(cfg)


def test_runs_path_is_selected():
    c = _client()
    try:
        assert c._engine.executor.supports_runs_metadata
        bf = c.get_bloom_filter("sel")
        bf.try_init(1000, 0.01)
        fut = bf.add_all_async(np.arange(10, dtype=np.uint64))
        fut.result()
        # The segment key for the runs path is distinct.
        assert ("bloom_mixk_runs" in str(k) for k in c._engine.executor._jit_cache)
        keys = [k for k in c._engine.executor._jit_cache if k[0] == "bloom_mixk_runs"]
        assert keys, "runs-metadata kernel was not compiled"
    finally:
        c.shutdown()


def test_runs_multi_tenant_segment_matches_golden():
    """Many tenants' chunks coalesce into one segment; results must match
    a per-tenant golden check."""
    c = _client()
    try:
        n_t = 7
        fs = []
        for t in range(n_t):
            bf = c.get_bloom_filter(f"rt{t}")
            bf.try_init(5000, 0.01)
            fs.append(bf)
        rng = np.random.default_rng(1)
        loads = [rng.integers(0, 10_000, 300).astype(np.uint64) for _ in range(n_t)]
        futs = [fs[t].add_all_async(loads[t]) for t in range(n_t)]
        for f in futs:
            f.result()
        # Every loaded key must be present; disjoint high keys mostly not.
        for t in range(n_t):
            assert int(np.sum(fs[t].contains_each(loads[t]))) == len(loads[t])
            miss = rng.integers(1 << 40, 1 << 41, 500).astype(np.uint64)
            fp = int(np.sum(fs[t].contains_each(miss)))
            assert fp < 50  # ~1% nominal
    finally:
        c.shutdown()


def test_runs_mixed_add_contains_order_within_segment():
    """An add submitted before a contains of the same key (same segment)
    must be observed — the sequential mixed kernel semantics."""
    c = _client(batch_window_us=5000)
    try:
        bf = c.get_bloom_filter("ord")
        bf.try_init(2000, 0.01)
        keys = np.arange(100, dtype=np.uint64)
        fa = bf.add_all_async(keys)
        fc = bf.contains_all_async(keys)
        assert int(np.sum(fc.result())) == 100
        assert int(np.sum(fa.result())) == 100
    finally:
        c.shutdown()


def test_runs_variable_length_keys():
    """String keys with differing lengths force the per-op lengths path."""
    cfg = Config().use_tpu_sketch(
        coalesce=True, exact_add_semantics=True, min_bucket=64,
        batch_window_us=500,
    )
    c = redisson_tpu.create(cfg)
    try:
        bf = c.get_bloom_filter("vl")
        bf.try_init(2000, 0.01)
        short = [f"k{i}" for i in range(50)]
        long = [f"long-key-{'x' * (i % 17)}-{i}" for i in range(50)]
        f1 = bf.add_all_async(short)
        f2 = bf.add_all_async(long)
        f1.result(); f2.result()
        assert bf.contains_all(short) == 50
        assert bf.contains_all(long) == 50
        assert not bf.contains("absent-key")
    finally:
        c.shutdown()


def test_runs_many_tiny_chunks_exceeding_run_bucket():
    """Degenerate shape: >1024 single-op submits in one segment must grow
    the run bucket, not corrupt results."""
    c = _client(batch_window_us=50_000, max_batch=1 << 14)
    try:
        bf = c.get_bloom_filter("tiny")
        bf.try_init(20_000, 0.01)
        futs = [bf.add_async(np.array([i], dtype=np.uint64)) for i in range(1500)]
        for f in futs:
            f.result()
        got = int(np.sum(bf.contains_each(np.arange(1500, dtype=np.uint64))))
        assert got == 1500
    finally:
        c.shutdown()


def test_runs_empty_batch():
    c = _client()
    try:
        bf = c.get_bloom_filter("empty")
        bf.try_init(1000, 0.01)
        assert bf.add_all(np.array([], dtype=np.uint64)) == 0
        assert bf.contains_all(np.array([], dtype=np.uint64)) == 0
    finally:
        c.shutdown()
