"""Device-side scan chunking (tpu_executor._SCAN_CHUNK): batches larger
than the chunk run as ONE launch whose kernel lax.scans fixed-size
chunks — results must be bit-identical to the unchunked path / golden
model.  The chunk size is monkeypatched small so the test exercises
multi-chunk scans at CPU-friendly sizes."""

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.executor import tpu_executor


@pytest.fixture
def small_chunks(monkeypatch):
    monkeypatch.setattr(tpu_executor, "_SCAN_CHUNK", 1 << 12)


@pytest.fixture
def client():
    c = redisson_tpu.create(
        Config().use_tpu_sketch(min_bucket=64, exact_add_semantics=False,
                                coalesce=False)
    )
    yield c
    c.shutdown()


class TestScanChunkedBloom:
    def test_contains_matches_host_engine_across_chunks(
        self, small_chunks, client
    ):
        """Oracle: the host golden engine through the same public API and
        codec — identical key bytes hash to identical bits."""
        bf = client.get_bloom_filter("scan-bf")
        bf.try_init(50_000, 0.01)
        loaded = np.arange(20_000, dtype=np.uint64)
        bf.add_all(loaded)

        host = redisson_tpu.create(Config())  # host engine, same codec
        try:
            hbf = host.get_bloom_filter("scan-bf")
            hbf.try_init(50_000, 0.01)
            hbf.add_all(loaded)

            # 16k probe keys -> 4 scan chunks of 4k at the patched size
            rng = np.random.default_rng(1)
            probe = rng.integers(0, 40_000, 1 << 14).astype(np.uint64)
            got = bf.contains_each(probe)
            want = hbf.contains_each(probe)
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        finally:
            host.shutdown()

    def test_add_matches_golden_across_chunks(self, small_chunks, client):
        bf = client.get_bloom_filter("scan-bf-add")
        bf.try_init(50_000, 0.01)
        keys = np.arange(1 << 14, dtype=np.uint64)  # 4 chunks
        newly = bf.add_all_async(keys).result()
        assert newly.shape == keys.shape
        assert newly.sum() > 0.97 * len(keys)
        assert bool(np.all(bf.contains_each(keys)))

    def test_unaligned_batch_size(self, small_chunks, client):
        """A batch that is not a multiple of the chunk pads to the pow-2
        bucket; validity masking must keep results exact."""
        bf = client.get_bloom_filter("scan-bf-odd")
        bf.try_init(50_000, 0.01)
        keys = np.arange(777, 777 + (1 << 13) + 123, dtype=np.uint64)
        bf.add_all(keys)
        assert bool(np.all(bf.contains_each(keys)))
        misses = bf.contains_each(
            np.arange(500_000, 500_000 + 4096, dtype=np.uint64)
        )
        assert misses.mean() < 0.05

    def test_variable_length_keys_across_chunks(self, small_chunks):
        """Mixed-length (string) keys exercise the non-const-length scan
        branch."""
        c = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
        try:
            bf = c.get_bloom_filter("scan-bf-str")
            bf.try_init(50_000, 0.01)
            keys = [f"k{'x' * (i % 9)}{i}" for i in range(1 << 13)]
            bf.add_all(keys)
            assert all(bf.contains_each(keys))
            assert (
                np.mean(bf.contains_each([f"ghost{i}" for i in range(4096)]))
                < 0.05
            )
        finally:
            c.shutdown()


class TestScanChunkedHll:
    def test_hll_estimate_across_chunks(self, small_chunks, client):
        h = client.get_hyper_log_log("scan-hll")
        n = 1 << 14
        changed = h.add_all_async(np.arange(n, dtype=np.uint64)).result()
        assert changed is True or changed  # whole-batch changed flag
        est = h.count()
        assert abs(est - n) / n < 0.05

    def test_hll_matches_single_launch_path(self, small_chunks, client):
        """The scan-chunked registers must be IDENTICAL to the unchunked
        scatter-max (max-merge is order-independent)."""
        h1 = client.get_hyper_log_log("scan-hll-a")
        keys = np.random.default_rng(2).integers(
            0, 1 << 40, 1 << 14
        ).astype(np.uint64)
        h1.add_all_async(keys).result()
        est_chunked = h1.count()

        tpu_executor._SCAN_CHUNK = 1 << 20  # restore: single-launch path
        try:
            h2 = client.get_hyper_log_log("scan-hll-b")
            h2.add_all_async(keys).result()
            assert h2.count() == est_chunked
        finally:
            tpu_executor._SCAN_CHUNK = 1 << 12


class TestExecutorSweepFixes:
    """Regressions for the round-5 executor high-effort sweep."""

    def test_contains_many_on_coalescing_engine(self, small_chunks):
        """The host-concat single-launch path must NOT engage on a
        coalescing engine (its mixed-keys kernel has no scan chunking);
        the pipelined per-batch form must still produce exact results."""
        c = redisson_tpu.create(
            Config().use_tpu_sketch(min_bucket=64, coalesce=True,
                                    batch_window_us=200)
        )
        try:
            bf = c.get_bloom_filter("cm-coal")
            bf.try_init(50_000, 0.01)
            keys = np.arange(4096, dtype=np.uint64)
            bf.add_all(keys)
            batches = [keys[i : i + 512] for i in range(0, 4096, 512)]
            results = bf.contains_many(batches)
            assert all(bool(np.all(r)) for r in results)
        finally:
            c.shutdown()

    def test_non_multiple_min_bucket_rounds_to_chunk(self, small_chunks):
        """A custom min_bucket that is not a multiple of the scan chunk
        must still take the chunked path (rounded UP), never the giant
        single launch."""
        c = redisson_tpu.create(
            Config().use_tpu_sketch(min_bucket=(1 << 12) + 96,
                                    coalesce=False,
                                    exact_add_semantics=False)
        )
        try:
            bf = c.get_bloom_filter("cm-odd")
            bf.try_init(50_000, 0.01)
            keys = np.arange(1 << 13, dtype=np.uint64)
            bf.add_all(keys)
            assert bool(np.all(bf.contains_each(keys)))
        finally:
            c.shutdown()

    def test_collect_group_odd_sizes_resolve_exact(self, client):
        """Groups whose size is not a power of 8 exercise the padded
        concat tree (duplicated pad results sliced off at resolution)."""
        bf = client.get_bloom_filter("cg-odd")
        bf.try_init(50_000, 0.01)
        loaded = np.arange(10_000, dtype=np.uint64)
        bf.add_all(loaded)
        from redisson_tpu.executor.tpu_executor import defer_host_fetch

        for g in (2, 3, 7, 9, 10, 17):
            batches = [
                np.arange(i * 256, (i + 1) * 256, dtype=np.uint64)
                for i in range(g)
            ]
            with defer_host_fetch():
                futs = [bf.contains_all_async(b) for b in batches]
            results = client.collect(futs)
            assert len(results) == g
            for b, r in zip(batches, results):
                want = b < 10_000
                np.testing.assert_array_equal(np.asarray(r), want)

    def test_collect_mixed_sizes_singleton_sigs(self, client):
        """Different batch sizes -> singleton signature groups: collect
        must still resolve every future correctly (async prefetch path)."""
        bf = client.get_bloom_filter("cg-mixed")
        bf.try_init(50_000, 0.01)
        bf.add_all(np.arange(5000, dtype=np.uint64))
        from redisson_tpu.executor.tpu_executor import defer_host_fetch

        sizes = [64, 200, 700, 1500]
        batches = [np.arange(s, dtype=np.uint64) for s in sizes]
        with defer_host_fetch():
            futs = [bf.contains_all_async(b) for b in batches]
        results = client.collect(futs)
        for b, r in zip(batches, results):
            np.testing.assert_array_equal(np.asarray(r), b < 5000)
