"""RESP scripting gate (ISSUE 2 satellites): EVAL/EVALSHA/SCRIPT/
FUNCTION/FCALL are Python-RCE surfaces — disabled by default, enable
refuses without requirepass or a loopback bind, and EVAL registers
sha1(body) so EVALSHA works as in Redis."""

import hashlib

import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.serve.resp import RespServer

from test_resp_server import RespClient


@pytest.fixture
def client():
    cl = redisson_tpu.create(Config())
    yield cl
    cl.shutdown()


def test_scripts_disabled_by_default(client):
    srv = RespServer(client)
    c = RespClient(srv.host, srv.port)
    try:
        assert c.cmd("PING") == "PONG"
        for cmd in (
            ("EVAL", "1 + 1", 0),
            ("EVALSHA", "f" * 40, 0),
            ("SCRIPT", "LOAD", "1"),
            ("FUNCTION", "LIST"),
            ("FCALL", "nope", 0),
            ("FCALL_RO", "nope", 0),
        ):
            with pytest.raises(RuntimeError, match="scripting is disabled"):
                c.cmd(*cmd)
    finally:
        c.close()
        srv.close()


def test_scripts_disabled_inside_multi(client):
    """The gate fires at queue time (the _dispatch check precedes the
    MULTI branch), so a disabled EVAL can never ride a transaction."""
    srv = RespServer(client)
    c = RespClient(srv.host, srv.port)
    try:
        assert c.cmd("MULTI") == "OK"
        with pytest.raises(RuntimeError, match="scripting is disabled"):
            c.cmd("EVAL", "1", 0)
        assert c.cmd("DISCARD") == "OK"
    finally:
        c.close()
        srv.close()


def test_enable_on_loopback_without_password_is_allowed(client):
    srv = RespServer(client, enable_python_scripts=True)  # 127.0.0.1
    c = RespClient(srv.host, srv.port)
    try:
        assert c.cmd("EVAL", "1 + 2", 0) == 3
    finally:
        c.close()
        srv.close()


def test_enable_on_open_bind_without_password_refuses(client):
    with pytest.raises(ValueError, match="requirepass"):
        RespServer(client, host="0.0.0.0", enable_python_scripts=True)


def test_enable_on_open_bind_with_password_is_allowed(client):
    srv = RespServer(
        client, host="0.0.0.0", requirepass="pw",
        enable_python_scripts=True,
    )
    c = RespClient("127.0.0.1", srv.port)
    try:
        assert c.cmd("AUTH", "pw") == "OK"
        assert c.cmd("EVAL", "2 + 2", 0) == 4
    finally:
        c.close()
        srv.close()


def test_config_flag_enables_scripts(client):
    client.config.enable_python_scripts = True
    srv = RespServer(client)
    c = RespClient(srv.host, srv.port)
    try:
        assert c.cmd("EVAL", "len(ARGV)", 0, "a", "b") == 2
    finally:
        c.close()
        srv.close()
        client.config.enable_python_scripts = False


def test_eval_registers_sha_for_evalsha(client):
    """EVAL then EVALSHA of the same body must hit, like redis-server
    (EVAL caches the script under sha1(body))."""
    srv = RespServer(client, enable_python_scripts=True)
    c = RespClient(srv.host, srv.port)
    try:
        body = b"int(ARGV[0]) * 3"
        sha = hashlib.sha1(body).hexdigest()
        assert c.cmd("SCRIPT", "EXISTS", sha) == [0]
        assert c.cmd("EVAL", body, 0, "5") == 15
        assert c.cmd("SCRIPT", "EXISTS", sha) == [1]
        assert c.cmd("EVALSHA", sha, 0, "7") == 21
        # Registered on the Python-side ScriptService too.
        assert client.get_script().eval(sha, [], [b"2"]) == 6
        # SCRIPT FLUSH still clears EVAL-registered scripts.
        assert c.cmd("SCRIPT", "FLUSH") == "OK"
        with pytest.raises(RuntimeError, match="NOSCRIPT"):
            c.cmd("EVALSHA", sha, 0, "1")
    finally:
        c.close()
        srv.close()
