"""`python -m redisson_tpu` — the standalone-server deployment shape
(redis-server analog).  Boots a real subprocess, drives it over TCP with
the framing-aware RespClient, restarts it, and verifies
snapshot-on-shutdown persistence (replies acked before SIGTERM must
survive — the server drains connections before the final snapshot)."""

import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

from test_resp_server import RespClient

REPO = Path(__file__).parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(port, snap_dir):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    flags = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    env["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=2"]
    )
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.Popen(
        [
            sys.executable, "-m", "redisson_tpu",
            "--port", str(port),
            "--snapshot-dir", str(snap_dir),
            "--platform", "cpu",
        ],
        cwd=str(REPO),
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )


def _connect(port, deadline_s=90.0) -> RespClient:
    t0 = time.monotonic()
    while True:
        try:
            # Generous socket timeout: a cold first JAX compile can stall
            # the first sketch command well past 10s.
            return RespClient("127.0.0.1", port, timeout=120)
        except OSError:
            if time.monotonic() - t0 > deadline_s:
                raise
            time.sleep(0.2)


def test_standalone_server_round_trip(tmp_path):
    port = _free_port()
    proc = _spawn(port, tmp_path / "snap")
    try:
        c = _connect(port)
        assert c.cmd("PING") == "PONG"
        assert c.cmd("SET", "cli-k", "v") == "OK"
        assert c.cmd("BF.RESERVE", "cli-bf", "0.01", "1000") == "OK"
        assert c.cmd("BF.ADD", "cli-bf", "alpha") == 1
        assert c.cmd("BF.EXISTS", "cli-bf", "alpha") == 1
        c.close()
        # Graceful shutdown drains connections, then snapshots.
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=120)
        assert proc.returncode == 0, out[-2000:]
        # Reboot on the same snapshot dir: sketch state survives.
        port2 = _free_port()
        proc2 = _spawn(port2, tmp_path / "snap")
        try:
            c2 = _connect(port2)
            assert c2.cmd("BF.EXISTS", "cli-bf", "alpha") == 1
            assert c2.cmd("BF.EXISTS", "cli-bf", "nope") == 0
            # The HOST keyspace persists too (grid_store.bin).
            assert c2.cmd("GET", "cli-k") == b"v"
            c2.close()
        finally:
            proc2.send_signal(signal.SIGTERM)
            proc2.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=30)
