"""Coordination services (SURVEY.md §2.3 services row): executor service,
remote service, transactions, script service, live objects, map-reduce.
"""

import threading
import time

import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.grid import TransactionException


@pytest.fixture
def client():
    c = redisson_tpu.create(Config())
    yield c
    c.shutdown()


class TestExecutorService:
    def test_submit_runs_on_workers(self, client):
        ex = client.get_executor_service("ex1")
        ex.register_workers(2)
        futs = [ex.submit(lambda i=i: i * i) for i in range(10)]
        assert [f.result(5.0) for f in futs] == [i * i for i in range(10)]
        ex.shutdown()

    def test_no_workers_means_tasks_queue(self, client):
        ex = client.get_executor_service("ex2")
        fut = ex.submit(lambda: 42)
        with pytest.raises(TimeoutError):
            fut.result(0.1)
        assert ex.get_task_count() == 1
        ex.register_workers(1)  # the RedissonNode shows up
        assert fut.result(5.0) == 42
        ex.shutdown()

    def test_task_error_propagates(self, client):
        ex = client.get_executor_service("ex3")
        ex.register_workers(1)

        def boom():
            raise ValueError("task failed")

        with pytest.raises(ValueError, match="task failed"):
            ex.submit(boom).result(5.0)
        ex.shutdown()

    def test_schedule_delay(self, client):
        ex = client.get_executor_service("ex4")
        ex.register_workers(1)
        t0 = time.monotonic()
        fut = ex.schedule(lambda: "late", 0.15)
        assert fut.result(5.0) == "late"
        assert time.monotonic() - t0 >= 0.14
        ex.shutdown()

    def test_fixed_rate_and_cancel(self, client):
        ex = client.get_executor_service("ex5")
        ex.register_workers(1)
        hits = []
        fut = ex.schedule_at_fixed_rate(lambda: hits.append(1), 0.01, 0.05)
        deadline = time.time() + 3
        while time.time() < deadline and len(hits) < 3:
            time.sleep(0.02)
        assert len(hits) >= 3
        fut.cancel()
        n = len(hits)
        time.sleep(0.2)
        assert len(hits) <= n + 1  # at most one in-flight fire after cancel
        ex.shutdown()


class TestRemoteService:
    def test_sync_invocation(self, client):
        class Calc:
            def mul(self, a, b):
                return a * b

        rs = client.get_remote_service()
        rs.register("Calc", Calc(), workers=2)
        proxy = rs.get("Calc")
        assert proxy.mul(6, 7) == 42

    def test_async_invocation(self, client):
        class Echo:
            def say(self, s):
                return f"echo:{s}"

        rs = client.get_remote_service()
        rs.register("Echo", Echo())
        fut = rs.get_async("Echo").say("hi")
        assert fut.result(5.0) == "echo:hi"

    def test_unregistered_raises(self, client):
        rs = client.get_remote_service()
        with pytest.raises(RuntimeError, match="no workers"):
            rs.get("Nope").anything()


class TestTransaction:
    def test_commit_applies_atomically(self, client):
        tx = client.create_transaction()
        tx.get_bucket("tb").set("v1")
        tx.get_map("tm").put("k", 1)
        # Nothing visible before commit.
        assert client.get_bucket("tb").get() is None
        tx.commit()
        assert client.get_bucket("tb").get() == "v1"
        assert client.get_map("tm").get("k") == 1

    def test_conflicting_write_aborts(self, client):
        client.get_bucket("cb").set("original")
        tx = client.create_transaction()
        assert tx.get_bucket("cb").get() == "original"  # read-validated
        client.get_bucket("cb").set("sneaky concurrent write")
        tx.get_bucket("cb").set("tx value")
        with pytest.raises(TransactionException):
            tx.commit()
        assert client.get_bucket("cb").get() == "sneaky concurrent write"

    def test_rollback_discards(self, client):
        tx = client.create_transaction()
        tx.get_bucket("rb").set("x")
        tx.rollback()
        assert client.get_bucket("rb").get() is None
        with pytest.raises(TransactionException):
            tx.commit()  # single-shot

    def test_read_your_writes_inside_tx(self, client):
        tx = client.create_transaction()
        b = tx.get_bucket("ry")
        b.set("mine")
        assert b.get() == "mine"
        m = tx.get_map("rym")
        m.put("k", 5)
        assert m.get("k") == 5
        tx.commit()


class TestScriptService:
    def test_atomic_procedure(self, client):
        s = client.get_script()

        def incr_both(cl, keys, args):
            a = cl.get_atomic_long(keys[0])
            b = cl.get_atomic_long(keys[1])
            a.add_and_get(args[0])
            b.add_and_get(args[0])
            return a.get() + b.get()

        s.register("incr-both", incr_both)
        out = s.eval("incr-both", keys=["x", "y"], args=[5])
        assert out == 10
        assert client.get_atomic_long("x").get() == 5

    def test_noscript(self, client):
        with pytest.raises(KeyError, match="NOSCRIPT"):
            client.get_script().eval("missing")


class TestLiveObjects:
    def test_persist_and_get(self, client):
        class Account:
            def __init__(self, id, owner, balance):
                self.id = id
                self.owner = owner
                self.balance = balance

        svc = client.get_live_object_service()
        live = svc.persist(Account(7, "ada", 100))
        # Another handle sees the same state (map-backed).
        again = svc.get("Account", 7)
        assert again.owner == "ada"
        again.balance = 250
        assert live.balance == 250
        assert svc.exists(Account, 7)
        assert svc.delete(Account, 7)
        assert not svc.exists(Account, 7)


class TestMapReduce:
    def test_word_count(self, client):
        m = client.get_map("docs")
        m.put("d1", "a b a")
        m.put("d2", "b c")
        m.put("d3", "a")
        mr = client.get_map_reduce(m, workers=3, chunk_size=1)
        out = (
            mr.mapper(lambda k, v: [(w, 1) for w in v.split()])
            .reducer(lambda k, vals: sum(vals))
            .execute()
        )
        assert out == {"a": 3, "b": 2, "c": 1}


class TestServiceHandleSharing:
    """r3 review: services are name-shared — workers registered through
    one handle run tasks submitted through another."""

    def test_executor_service_shared_across_handles(self, client):
        client.get_executor_service("shared").register_workers(1)
        fut = client.get_executor_service("shared").submit(lambda: "ran")
        assert fut.result(5.0) == "ran"

    def test_remote_service_shared_across_handles(self, client):
        class Svc:
            def hi(self):
                return "hello"

        client.get_remote_service().register("Svc", Svc())
        assert client.get_remote_service().get("Svc").hi() == "hello"

    def test_schedule_after_shutdown_raises(self, client):
        import pytest as _pytest

        ex = client.get_executor_service("sd")
        ex.shutdown()
        with _pytest.raises(RuntimeError):
            ex.schedule(lambda: 1, 0.01)
        # A fresh handle after shutdown gets a working service again.
        ex2 = client.get_executor_service("sd")
        ex2.register_workers(1)
        assert ex2.submit(lambda: 2).result(5.0) == 2


class TestServicesDepthR4:
    """Round-4 services depth (VERDICT #9): transactional sets, cron
    scheduling, RemoteService ack timeouts."""

    def test_transactional_set(self, client):
        s = client.get_set("txs")
        s.add("pre")
        tx = client.create_transaction()
        ts = tx.get_set("txs")
        assert ts.contains("pre") is True
        assert ts.add("new") is True
        assert ts.add("new") is False  # staged membership visible
        assert ts.remove("pre") is True
        tx.commit()
        assert s.contains("new") and not s.contains("pre")

    def test_transactional_set_conflict_detected(self, client):
        s = client.get_set("txs2")
        tx = client.create_transaction()
        ts = tx.get_set("txs2")
        assert ts.contains("x") is False  # snapshot: absent
        s.add("x")  # concurrent writer invalidates the read
        ts.add("y")
        import pytest as _pytest

        from redisson_tpu.grid.services import TransactionException

        with _pytest.raises(TransactionException):
            tx.commit()
        assert not s.contains("y")  # log not applied

    def test_cron_expression_parsing_and_next(self):
        from datetime import datetime

        from redisson_tpu.grid.cron import CronExpression

        # every minute
        c = CronExpression("* * * * *")
        base = datetime(2026, 7, 30, 12, 0, 30).timestamp()
        nxt = datetime.fromtimestamp(c.next_after(base))
        assert (nxt.minute, nxt.second) == (1, 0)
        # Quartz 6-field with seconds: every 15s
        c = CronExpression("*/15 * * * * ?")
        nxt = datetime.fromtimestamp(c.next_after(base))
        assert nxt.second == 45 and nxt.minute == 0
        # specific time daily
        c = CronExpression("0 30 4 * * ?")
        nxt = datetime.fromtimestamp(c.next_after(base))
        assert (nxt.hour, nxt.minute, nxt.second) == (4, 30, 0)
        # day-of-week names + range
        c = CronExpression("0 0 9 ? * MON-FRI")
        nxt = datetime.fromtimestamp(c.next_after(base))
        assert nxt.weekday() < 5 and nxt.hour == 9
        # 5-field classic
        c = CronExpression("30 14 * * *")
        nxt = datetime.fromtimestamp(c.next_after(base))
        assert (nxt.hour, nxt.minute) == (14, 30)
        # Quartz 'n/step' means FROM n TO max — including step 1
        # ('0/1 * ...' is the standard spelling of 'every minute').
        c = CronExpression("0 0/1 * * * ?")
        nxt = datetime.fromtimestamp(c.next_after(base))
        assert (nxt.minute, nxt.second) == (1, 0)
        c = CronExpression("0 5/10 * * * ?")
        assert c.minutes == frozenset(range(5, 60, 10))
        import pytest as _pytest

        with _pytest.raises(ValueError):
            CronExpression("bad expr")

    def test_schedule_cron_fires_and_rearms(self, client):
        import time

        ex = client.get_executor_service("cronx")
        ex.register_workers(1)
        hits = []
        # "every second" in Quartz grammar — fast enough to observe twice
        fut = ex.schedule_cron(lambda: hits.append(time.time()), "* * * * * ?")
        deadline = time.time() + 5
        while len(hits) < 2 and time.time() < deadline:
            time.sleep(0.05)
        assert len(hits) >= 2, "cron task did not fire twice"
        assert fut.cancel()
        n = len(hits)
        time.sleep(1.5)
        assert len(hits) <= n + 1  # cancel stops the re-arm

    def test_remote_service_ack_timeout(self, client):
        import pytest as _pytest

        from redisson_tpu.grid.services import (
            RemoteServiceAckTimeoutException,
        )

        rs = client.get_remote_service("acks")

        class Impl:
            def ping(self):
                return "pong"

        # Registered with ZERO workers: nothing can ack -> fast-fail with
        # the typed ack exception, well before the execution timeout.
        rs.register("svc", Impl(), workers=0)
        proxy = rs.get("svc", timeout_seconds=30.0, ack_timeout_seconds=0.3)
        import time

        t0 = time.monotonic()
        with _pytest.raises(RemoteServiceAckTimeoutException):
            proxy.ping()
        assert time.monotonic() - t0 < 5.0
        # With a live worker the same proxy acks and completes.
        rs2 = client.get_remote_service("acks2")
        rs2.register("svc", Impl(), workers=1)
        assert rs2.get("svc", ack_timeout_seconds=2.0).ping() == "pong"


class TestServicesReviewFixesR4:
    def test_txset_absent_vs_empty_entry_not_spurious_conflict(self, client):
        s = client.get_set("txs3")
        tx = client.create_transaction()
        ts = tx.get_set("txs3")
        assert ts.contains("y") is False  # set entry doesn't even exist yet
        s.add("x")  # creates the entry; 'y' membership UNCHANGED (False)
        ts.add("z")
        tx.commit()  # must NOT raise: observed membership still False
        assert s.contains("z") and s.contains("x")

    def test_cancelled_cron_task_does_not_leak(self, client):
        import time

        ex = client.get_executor_service("leak")
        ex.register_workers(1)
        futs = [
            ex.schedule_cron(lambda: None, "* * * * * ?") for _ in range(5)
        ]
        for f in futs:
            assert f.cancel()
        time.sleep(1.5)  # let the timer sweep the cancelled entries
        assert len(ex._futures) == 0
        assert len(ex._periodic) == 0

    def test_cron_dow_conventions(self):
        from redisson_tpu.grid.cron import CronExpression

        # Quartz 6-field numeric: 1=SUN .. 7=SAT
        q = CronExpression("0 0 12 ? * 1")
        assert q.dow == frozenset({0})  # Sunday internally
        q = CronExpression("0 0 12 ? * 7")
        assert q.dow == frozenset({6})  # Saturday
        # classic 5-field numeric: 0=SUN .. 6=SAT, 7 also Sunday
        c = CronExpression("0 12 * * 0")
        assert c.dow == frozenset({0})
        c = CronExpression("0 12 * * 7")
        assert c.dow == frozenset({0})
        # names identical in both
        assert CronExpression("0 0 12 ? * SUN").dow == frozenset({0})
        assert CronExpression("0 12 * * SAT").dow == frozenset({6})

    def test_cron_dom_dow_or_semantics(self):
        from datetime import datetime

        from redisson_tpu.grid.cron import CronExpression

        # 'midnight on the 13th OR every Friday' (vixie OR rule)
        c = CronExpression("0 0 13 * FRI")
        # 2026-02-06 is a Friday but not the 13th
        assert c._minute_matches(datetime(2026, 2, 6, 0, 0))
        # 2026-02-13 is Friday the 13th
        assert c._minute_matches(datetime(2026, 2, 13, 0, 0))
        # 2026-03-13 is a Friday... pick a non-Friday 13th: 2026-04-13 (Mon)
        assert c._minute_matches(datetime(2026, 4, 13, 0, 0))
        # non-13th non-Friday
        assert not c._minute_matches(datetime(2026, 2, 9, 0, 0))
        # One side unrestricted keeps AND semantics
        c = CronExpression("0 0 * * FRI")
        assert c._minute_matches(datetime(2026, 2, 6, 0, 0))
        assert not c._minute_matches(datetime(2026, 2, 9, 0, 0))
