"""Public-API end-to-end tests on the 8-shard virtual CPU mesh.

The sharded-cluster analog of the reference's "many redis-servers on one
host" integration tests (SURVEY.md §4): same client code as single-device
mode, with ``use_tpu_sketch(num_shards=8)`` routing every op through
ShardedTpuCommandExecutor's shard_map kernels and ICI collectives.
"""

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config


@pytest.fixture(params=["coalesced", "direct"])
def client(request):
    cfg = Config().use_tpu_sketch(
        num_shards=8,
        min_bucket=64,
        coalesce=(request.param == "coalesced"),
        batch_window_us=100,
    )
    cl = redisson_tpu.create(cfg)
    yield cl
    cl.shutdown()


@pytest.fixture
def host_client():
    return redisson_tpu.create(Config())


def test_bloom_end_to_end_matches_host(client, host_client):
    keys = [f"key-{i}" for i in range(500)]
    probes = [f"probe-{i}" for i in range(500)]
    for cl in (client, host_client):
        bf = cl.get_bloom_filter("bf")
        assert bf.try_init(2000, 0.01) is True
        assert bf.try_init(2000, 0.01) is False
        bf.add_all(keys)
    tpu_bf = client.get_bloom_filter("bf")
    host_bf = host_client.get_bloom_filter("bf")
    assert all(tpu_bf.contains_each(keys))
    # Same hash material in both engines -> identical membership answers.
    np.testing.assert_array_equal(
        tpu_bf.contains_each(probes), host_bf.contains_each(probes)
    )
    assert abs(tpu_bf.count() - host_bf.count()) == 0


def test_many_tenants_spread_over_shards(client):
    # More tenants than shards: forces multi-row-per-shard placement and
    # pool growth across the mesh.
    filters = []
    for t in range(20):
        bf = client.get_bloom_filter(f"tenant-{t}")
        bf.try_init(500, 0.01)
        bf.add_all([f"t{t}-k{i}" for i in range(50)])
        filters.append(bf)
    for t, bf in enumerate(filters):
        assert all(bf.contains_each([f"t{t}-k{i}" for i in range(50)]))
        # Other tenants' keys are (almost surely) absent.
        misses = bf.contains_each([f"t{(t + 1) % 20}-k{i}" for i in range(50)])
        assert np.sum(misses) <= 3


def test_hll_count_and_merge(client, host_client):
    for cl in (client, host_client):
        h1 = cl.get_hyper_log_log("h1")
        h2 = cl.get_hyper_log_log("h2")
        h1.add_all([f"a-{i}" for i in range(5000)])
        h2.add_all([f"b-{i}" for i in range(5000)])
        h1.merge_with("h2")
    tpu = client.get_hyper_log_log("h1").count()
    host = host_client.get_hyper_log_log("h1").count()
    assert tpu == host  # identical registers -> identical estimate
    assert abs(tpu - 10000) / 10000 < 0.05


def test_hll_add_returns_changed(client):
    h = client.get_hyper_log_log("chg")
    assert h.add("x") is True
    assert h.add("x") is False


def test_bitset_ops_match_host(client, host_client):
    rng = np.random.default_rng(3)
    idx = rng.integers(0, 5000, 300).astype(np.uint32)
    for cl in (client, host_client):
        bs = cl.get_bit_set("bits")
        bs.set_many(idx)
        bs.flip(7)
        bs.set_range(100, 164)
        cl._engine.bitset_bitop("bits2", ("bits",), "not")
    a, b = client.get_bit_set("bits"), host_client.get_bit_set("bits")
    assert a.cardinality() == b.cardinality()
    assert a.length() == b.length()
    assert a.to_byte_array() == b.to_byte_array()
    assert (
        client.get_bit_set("bits2").cardinality()
        == host_client.get_bit_set("bits2").cardinality()
    )
    probe = rng.integers(0, 6000, 200).astype(np.uint32)
    np.testing.assert_array_equal(a.get_many(probe), b.get_many(probe))


def test_bitset_growth_migration(client):
    bs = client.get_bit_set("grower")
    bs.set(10)
    bs.set(100_000)  # forces size-class migration across the mesh
    assert bs.get(10) is True
    assert bs.get(100_000) is True
    assert bs.cardinality() == 2


def test_cms_estimates_match_host(client, host_client):
    rng = np.random.default_rng(11)
    stream = [f"item-{int(z)}" for z in rng.zipf(1.3, 3000)]
    for cl in (client, host_client):
        c = cl.get_count_min_sketch("cms")
        c.try_init(4, 1 << 10)
        c.add_all(stream)
        c2 = cl.get_count_min_sketch("cms2")
        c2.try_init(4, 1 << 10)
        c2.add_all(stream[:500])
        c.merge("cms2")
    probes = [f"item-{i}" for i in range(1, 30)]
    tpu = client.get_count_min_sketch("cms").estimate_all(probes)
    host = host_client.get_count_min_sketch("cms").estimate_all(probes)
    np.testing.assert_array_equal(np.asarray(tpu), np.asarray(host))


def test_delete_rename_exists(client):
    bf = client.get_bloom_filter("adm")
    bf.try_init(100, 0.01)
    bf.add("v")
    assert client._engine.exists("adm")
    assert client._engine.rename("adm", "adm2")
    assert not client._engine.exists("adm")
    assert client.get_bloom_filter("adm2").contains("v")
    assert client._engine.delete("adm2")
    assert not client._engine.exists("adm2")


def test_concurrent_multi_tenant_traffic(client):
    import threading

    errors = []

    def worker(t):
        try:
            bf = client.get_bloom_filter(f"conc-{t}")
            bf.try_init(1000, 0.01)
            for chunk in range(5):
                keys = [f"w{t}-c{chunk}-{i}" for i in range(40)]
                bf.add_all(keys)
                assert all(bf.contains_each(keys))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(6)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert not errors
