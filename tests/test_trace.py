"""Fleet telemetry plane (ISSUE 13): distributed tracing (obs/trace.py),
the LATENCY monitor (obs/latency.py), MONITOR, the RTPU.TRACE wire
prelude, bounded-store churn guards, and the slow-marked 3-node
subprocess trace test (one trace across client legs, per-node serving
spans, and device launches)."""

import json
import time

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.codecs import LongCodec
from redisson_tpu.obs import Observability
from redisson_tpu.obs import trace as trace_mod
from redisson_tpu.obs.latency import MAX_EVENTS, MAX_SAMPLES, LatencyMonitor
from redisson_tpu.obs.trace import Tracer
from redisson_tpu.serve.resp import RespServer

from test_resp_server import RespClient


# -- tracer core ------------------------------------------------------------


def test_sampling_off_is_disabled_and_free():
    t = Tracer()
    assert t.sample_rate == 0.0
    # rate 0 -> maybe_start never samples.
    assert t.maybe_start("x") is None
    with pytest.raises(ValueError):
        t.set_sample_rate(1.5)
    with pytest.raises(ValueError):
        t.set_sample_rate(-0.1)


def test_head_sampling_and_span_recording():
    t = Tracer(sample_rate=1.0)
    try:
        assert trace_mod.ENABLED is True
        root = t.maybe_start("root")
        assert root is not None
        assert len(root.trace_id) == 32 and len(root.span_id) == 16
        child = t.start_child(root, "child")
        child.annotate("k", 7)
        child.end()
        root.end()
        traces = t.traces()
        assert list(traces) == [root.trace_id]
        spans = traces[root.trace_id]
        assert [s["name"] for s in spans] == ["child", "root"]
        assert spans[0]["parent_id"] == root.span_id
        assert spans[0]["attrs"]["k"] == 7
        assert spans[1]["parent_id"] == ""
        # JSON wire form round-trips.
        doc = json.loads(t.traces_json()[0])
        assert doc["trace_id"] == root.trace_id
    finally:
        t.set_sample_rate(0.0)
    assert trace_mod.ENABLED is False


def test_forced_span_ignores_local_rate():
    """Head-based sampling: a remote hop's decision binds this process
    even with local sampling off."""
    t = Tracer()  # rate 0
    span = t.start("hop", "ab" * 16, "cd" * 8)
    span.end()
    assert t.traces("ab" * 16)


def test_scope_nesting_and_current():
    t = Tracer(sample_rate=1.0)
    try:
        a = t.maybe_start("a")
        b = t.maybe_start("b")
        assert trace_mod.current() is None
        with trace_mod.scope(a.ctx()) as ca:
            assert trace_mod.current() is ca
            with trace_mod.scope(b.ctx()) as cb:
                assert trace_mod.current() is cb
            assert trace_mod.current() is ca
        assert trace_mod.current() is None
        a.end()
        b.abandon()
    finally:
        t.set_sample_rate(0.0)


def test_trace_ring_hard_bound_under_churn():
    """ISSUE 13 satellite: 100k-op churn cannot grow the span ring past
    its bound (no RT006-class leak)."""
    t = Tracer(max_spans=256)
    ctx = trace_mod.TraceContext(t, "ff" * 16, "ee" * 8)
    for i in range(100_000):
        t.record_span(ctx, f"n{i}", 0.0, 0.001)
    assert len(t.spans()) == 256
    assert t.evicted == 100_000 - 256
    st = t.stats()
    assert st["spans"] == 256 and st["max_spans"] == 256
    t.reset()
    assert t.spans() == []


def test_latency_monitor_semantics_and_bounds():
    lat = LatencyMonitor()
    # Disarmed (threshold 0): records nothing, one-compare cheap.
    assert not lat.record("command", 5000)
    assert lat.latest() == []
    lat.set_threshold_ms(100)
    assert not lat.record("command", 99)  # below threshold
    assert lat.record("command", 150)
    assert lat.record("command", 300)
    ((name, ts, last, mx),) = lat.latest()
    assert name == "command" and last == 300 and mx == 300
    assert [ms for _, ms in lat.history("command")] == [150, 300]
    # DOCTOR mentions the event and advice.
    assert "command" in lat.doctor()
    assert lat.reset("command") == 1
    assert lat.history("command") == []
    # 100k-op churn: per-event ring and event-name space both bounded.
    for i in range(100_000):
        lat.record(f"evt-{i % 100}", 200 + i % 7)
    st = lat.stats()
    assert st["events"] <= MAX_EVENTS
    assert st["samples"] <= MAX_EVENTS * MAX_SAMPLES
    with pytest.raises(ValueError):
        lat.set_threshold_ms(-1)


def test_observability_bundle_wires_telemetry():
    obs = Observability(trace_sample_rate=0.0, latency_threshold_ms=0)
    assert obs.trace.sample_rate == 0.0
    assert obs.latency.threshold_ms == 0
    # reset_op_stats rides the PUBLIC SpanRecorder.reset (satellite 6)
    # and clears the trace ring too.
    span = obs.spans.start("op", 4)
    span.stamp("d2h_fetch")
    span.finish()
    assert obs.spans.recent()
    obs.reset_op_stats()
    assert obs.spans.recent() == []
    assert obs.trace.spans() == []


# -- engine stitching -------------------------------------------------------


@pytest.fixture
def traced_client():
    cfg = Config().set_codec(LongCodec()).use_tpu_sketch(
        batch_window_us=100, min_bucket=64
    )
    cfg.trace_sample_rate = 1.0
    cl = redisson_tpu.create(cfg)
    yield cl
    cl.obs.trace.set_sample_rate(0.0)
    cl.shutdown()


def test_direct_api_trace_links_launch_phases(traced_client):
    cl = traced_client
    with cl.trace("batch") as span:
        assert span is not None
        bf = cl.get_bloom_filter("tr-bf")
        bf.try_init(10_000, 0.01)
        bf.add_all(np.arange(256, dtype=np.uint64))
    traces = cl.get_metrics()["traces"]
    spans = traces[span.trace_id]
    names = [s["name"] for s in spans]
    assert "batch" in names
    launches = [s for s in spans if s["name"].startswith("launch:")]
    assert launches, names
    for ls in launches:
        assert ls["parent_id"] == span.span_id  # parent link intact
        assert ls["attrs"]["links"] >= 1
        assert "device_dispatch_us" in ls["attrs"]


def test_fused_launch_records_n_parent_links():
    """Two traced requests whose ops ride ONE launch: the launch span
    lands in BOTH traces, each copy reporting links=2 (the
    cross-request batch-fusion economics, visible per trace)."""
    # Fixed long window (adaptive OFF — the controller would shrink it
    # under light load and flush request 1 before request 2 submits).
    cfg = Config().set_codec(LongCodec()).use_tpu_sketch(
        batch_window_us=300_000, adaptive_window=False, min_bucket=64
    )
    cfg.trace_sample_rate = 1.0
    cl = redisson_tpu.create(cfg)
    try:
        bf = cl.get_bloom_filter("fuse-bf")
        bf.try_init(10_000, 0.01)
        bf.add_all(np.arange(64, dtype=np.uint64))  # pool/ladder warm
        tids = []
        futs = []
        for i in range(2):
            with cl.trace("req") as span:
                assert span is not None
                tids.append(span.trace_id)
                futs.append(
                    bf.contains_all_async(
                        np.arange(10_000 + i * 64,
                                  10_000 + i * 64 + 64,
                                  dtype=np.uint64)
                    )
                )
        for f in futs:
            f.result()
        # Launch spans land from the COMPLETER thread — poll briefly.
        deadline = time.monotonic() + 5.0
        fused: list = []
        traces: dict = {}
        while not fused and time.monotonic() < deadline:
            traces = cl.obs.trace.traces()
            fused = [
                s
                for tid in tids
                for s in traces.get(tid, ())
                if s["name"].startswith("launch:")
                and s["attrs"]["links"] >= 2
            ]
            if not fused:
                time.sleep(0.02)
        assert fused, {
            t: [s["name"] for s in ss] for t, ss in traces.items()
        }
        # The fused launch appears in EVERY parent's trace.
        assert len({s["trace_id"] for s in fused}) == len(set(tids))
    finally:
        cl.obs.trace.set_sample_rate(0.0)
        cl.shutdown()


def test_coalesced_submits_link_once_per_trace():
    """One traced request whose K submits coalesce into one launch must
    record ONE launch span, not K duplicates (review regression: the
    per-submit link had no dedup and flooded the ring)."""
    cfg = Config().set_codec(LongCodec()).use_tpu_sketch(
        batch_window_us=300_000, adaptive_window=False, min_bucket=64
    )
    cfg.trace_sample_rate = 1.0
    cl = redisson_tpu.create(cfg)
    try:
        bf = cl.get_bloom_filter("dedup-bf")
        bf.try_init(10_000, 0.01)
        bf.add_all(np.arange(64, dtype=np.uint64))
        with cl.trace("one") as span:
            futs = [
                bf.contains_all_async(
                    np.arange(20_000 + i * 64, 20_000 + i * 64 + 64,
                              dtype=np.uint64)
                )
                for i in range(4)
            ]
        for f in futs:
            f.result()
        deadline = time.monotonic() + 5.0
        launches: list = []
        while time.monotonic() < deadline:
            spans = cl.obs.trace.traces(span.trace_id).get(
                span.trace_id, []
            )
            launches = [
                s for s in spans if s["name"].startswith("launch:")
            ]
            if launches:
                break
            time.sleep(0.02)
        assert launches
        # 4 submits, shared segments: one span per LAUNCH, with no
        # duplicate (trace, parent) pairs.
        keys = [(s["name"], s["parent_id"]) for s in launches]
        assert len(keys) == len(set(keys)), keys
        assert all(s["attrs"]["links"] == 1 for s in launches)
    finally:
        cl.obs.trace.set_sample_rate(0.0)
        cl.shutdown()


def test_execute_many_crossslot_does_not_strand_root_span():
    """Client-side CrossSlotError aborts the batch before anything
    executes — no sampled-but-never-recorded root span may leak
    (review regression, the RT011 class)."""
    from redisson_tpu.cluster.client import ClusterClient, CrossSlotError

    cc = ClusterClient.__new__(ClusterClient)  # no live cluster needed
    cc.tracer = Tracer(sample_rate=1.0)
    try:
        cc._slots = [None] * 16384
        cc._seeds = [("127.0.0.1", 1)]
        import threading as _th

        cc._table_lock = _th.Lock()
        cc._conns = {}
        cc._pool = None
        cc.obs = None
        cc.stats = {"scatter_batches": 0, "scatter_legs": 0}
        before = cc.tracer.sampled
        with pytest.raises(CrossSlotError):
            cc.execute_many([("MSET", "a", "1", "b", "2")])
        # Routing failed before the root span was minted: nothing was
        # sampled, nothing is stranded.
        assert cc.tracer.sampled == before
        assert cc.tracer.spans() == []
    finally:
        cc.tracer.set_sample_rate(0.0)


# -- RESP wire surface ------------------------------------------------------


@pytest.fixture
def resp():
    cl = redisson_tpu.create(Config())
    srv = RespServer(cl)
    conn = RespClient(srv.host, srv.port)
    yield conn, srv, cl
    cl.obs.trace.set_sample_rate(0.0)
    srv.close()
    cl.shutdown()


def test_trace_commands_and_config_over_resp(resp):
    conn, srv, cl = resp
    # Off by default: INFO telemetry reports rate 0, TRACE GET empty.
    info = conn.cmd("INFO", "telemetry").decode()
    assert "trace_sample_rate:0" in info
    assert "latency_monitor_threshold:0" in info
    assert conn.cmd("TRACE", "GET") == []
    # Arm via CONFIG SET; bounds are validated.
    with pytest.raises(RuntimeError):
        conn.cmd("CONFIG", "SET", "trace-sample-rate", "1.5")
    with pytest.raises(RuntimeError):
        conn.cmd("CONFIG", "SET", "trace-sample-rate", "nope")
    assert conn.cmd("CONFIG", "SET", "trace-sample-rate", "1") == "OK"
    assert conn.cmd("CONFIG", "GET", "trace-sample-rate") == [
        b"trace-sample-rate", b"1",
    ]
    conn.cmd("SET", "tk", "tv")
    assert conn.cmd("GET", "tk") == b"tv"
    docs = [json.loads(d) for d in conn.cmd("TRACE", "GET")]
    names = [s["name"] for d in docs for s in d["spans"]]
    assert "resp:SET" in names and "resp:GET" in names
    for d in docs:
        for s in d["spans"]:
            assert s["attrs"]["node"]  # node label rides every span
    assert conn.cmd("TRACE", "LEN") >= 2
    # TRACE SAMPLE mirrors CONFIG SET.
    assert conn.cmd("TRACE", "SAMPLE", "0") == "OK"
    assert conn.cmd("CONFIG", "GET", "trace-sample-rate") == [
        b"trace-sample-rate", b"0",
    ]
    assert conn.cmd("TRACE", "RESET") == "OK"
    assert conn.cmd("TRACE", "GET") == []
    assert any(b"SAMPLE" in h for h in conn.cmd("TRACE", "HELP"))


def test_rtpu_trace_prelude_is_one_shot(resp):
    """The wire prelude forces the NEXT command into the remote trace
    even with local sampling off, then burns (the ASKING shape)."""
    conn, srv, cl = resp
    tid, sid = "ab" * 16, "cd" * 8
    assert conn.cmd("RTPU.TRACE", tid, sid) == "OK"
    conn.cmd("SET", "pk", "pv")
    conn.cmd("GET", "pk")  # NOT traced: the prelude was consumed
    traces = cl.obs.trace.traces(tid)
    assert list(traces) == [tid]
    spans = traces[tid]
    assert [s["name"] for s in spans] == ["resp:SET"]
    assert spans[0]["parent_id"] == sid  # parent link intact
    # Malformed preludes refuse.
    with pytest.raises(RuntimeError):
        conn.cmd("RTPU.TRACE", "x", sid)
    with pytest.raises(RuntimeError):
        conn.cmd("RTPU.TRACE", tid)


def test_prelude_passes_over_asking(resp):
    """The migration pump sends RTPU.TRACE + ASKING + <cmd>: ASKING is
    itself a prelude and must not consume the trace context — the
    traced hop is the command AFTER both (review regression)."""
    conn, srv, cl = resp
    tid, sid = "12" * 16, "34" * 8
    assert conn.cmd("RTPU.TRACE", tid, sid) == "OK"
    # Non-cluster server refuses ASKING — the prelude must survive even
    # an ERRORED ASKING (the burn block skips it by name, not outcome).
    with pytest.raises(RuntimeError):
        conn.cmd("ASKING")
    conn.cmd("SET", "ask-k", "v")
    spans = cl.obs.trace.traces(tid).get(tid, [])
    assert [s["name"] for s in spans] == ["resp:SET"], spans
    assert spans[0]["parent_id"] == sid


def test_gc_of_armed_tracer_recomputes_enabled():
    """Dropping an armed tracer without disarming it must not leave the
    module guard stuck True (review regression: every hook in the
    process would pay the traced path forever)."""
    import gc

    t = Tracer(sample_rate=1.0)
    assert trace_mod.ENABLED is True
    del t
    gc.collect()
    assert trace_mod.ENABLED is False


def test_slowlog_captures_trace_id(resp):
    conn, srv, cl = resp
    assert conn.cmd("CONFIG", "SET", "slowlog-log-slower-than", "0") == "OK"
    # Untraced entries keep the classic 6-element shape.
    conn.cmd("PING")
    entry = conn.cmd("SLOWLOG", "GET", "1")[0]
    assert len(entry) == 6
    assert conn.cmd("CONFIG", "SET", "trace-sample-rate", "1") == "OK"
    conn.cmd("SET", "sk", "sv")
    entries = conn.cmd("SLOWLOG", "GET", "-1")
    traced = [e for e in entries if len(e) == 7 and e[3][0] == b"SET"]
    assert traced, entries
    tid = traced[0][6].decode()
    assert cl.obs.trace.traces(tid)  # the id resolves in the ring
    conn.cmd("TRACE", "SAMPLE", "0")


def test_latency_monitor_over_resp(resp):
    conn, srv, cl = resp
    # Disarmed: DOCTOR says so; LATEST empty.
    assert "disabled" in conn.cmd("LATENCY", "DOCTOR").decode()
    assert conn.cmd("LATENCY", "LATEST") == []
    with pytest.raises(RuntimeError):
        conn.cmd("CONFIG", "SET", "latency-monitor-threshold", "-5")
    assert conn.cmd(
        "CONFIG", "SET", "latency-monitor-threshold", "10"
    ) == "OK"
    conn.cmd("DEBUG", "SLEEP", "0.05")
    conn.cmd("PING")  # under threshold: no event
    rows = conn.cmd("LATENCY", "LATEST")
    assert rows and rows[0][0] == b"command"
    assert rows[0][2] >= 50 and rows[0][3] >= rows[0][2]
    hist = conn.cmd("LATENCY", "HISTORY", "command")
    assert len(hist) == 1 and hist[0][1] >= 50
    assert conn.cmd("LATENCY", "HISTORY", "absent") == []
    info = conn.cmd("INFO", "telemetry").decode()
    assert "latency_monitor_threshold:10" in info
    assert conn.cmd("LATENCY", "RESET", "command") == 1
    assert conn.cmd("LATENCY", "LATEST") == []
    assert any(b"DOCTOR" in h for h in conn.cmd("LATENCY", "HELP"))


def test_latency_fsync_stall_event_via_chaos(tmp_path):
    """Acceptance criterion: LATENCY HISTORY fsync-stall returns events
    after a chaos-injected journal.fsync latency fault."""
    cfg = Config().set_codec(LongCodec()).use_tpu_sketch(min_bucket=64)
    cfg.journal_dir = str(tmp_path / "journal")
    cfg.journal_fsync = "always"
    cfg.latency_monitor_threshold_ms = 20
    cl = redisson_tpu.create(cfg)
    srv = RespServer(cl)
    conn = RespClient(srv.host, srv.port)
    try:
        assert conn.cmd(
            "DEBUG", "INJECT", "journal.fsync", "latency", "1", "7",
            "0.05",
        ) == "OK"
        bf = cl.get_bloom_filter("fs-bf")
        bf.try_init(1000, 0.01)
        bf.add(1)  # acked only after the (stalled) fsync
        conn.cmd("WAIT", "0", "0")  # explicit fence rides another fsync
        hist = conn.cmd("LATENCY", "HISTORY", "fsync-stall")
        assert hist, conn.cmd("LATENCY", "LATEST")
        assert all(ms >= 20 for _, ms in hist)
        rows = {r[0]: r for r in conn.cmd("LATENCY", "LATEST")}
        assert b"fsync-stall" in rows
    finally:
        conn.cmd("DEBUG", "INJECT", "OFF")
        srv.close()
        cl.shutdown()


def test_monitor_streams_other_connections(resp):
    conn, srv, cl = resp
    mon = RespClient(srv.host, srv.port)
    try:
        assert mon.cmd("MONITOR") == "OK"
        conn.cmd("SET", "mk", "mval")
        conn.cmd("GET", "mk")
        # Monitor lines are +simple pushes; read two.
        lines = [mon._read_reply(), mon._read_reply()]
        assert any('"SET" "mk" "mval"' in ln for ln in lines), lines
        assert any('"GET" "mk"' in ln for ln in lines), lines
        # Credentials are redacted on the stream.
        with pytest.raises(RuntimeError):
            conn.cmd("AUTH", "monitor-secret")
        line = mon._read_reply()
        assert "monitor-secret" not in line and "(redacted)" in line
        info = conn.cmd("INFO", "telemetry").decode()
        assert "monitors:1" in info
        # Drain the INFO command's own feed line before leaving monitor
        # mode (the stream echoes it too).
        assert '"INFO"' in mon._read_reply()
        # RESET leaves monitor mode; subsequent commands are not fed.
        assert mon.cmd("RESET") == "RESET"
        conn.cmd("SET", "mk2", "v2")
        assert mon.cmd("PING") == "PONG"  # no buffered pushes in between
    finally:
        mon.close()


def test_monitor_disables_fusion_while_attached(resp):
    conn, srv, cl = resp
    assert not srv._monitors
    mon = RespClient(srv.host, srv.port)
    try:
        assert mon.cmd("MONITOR") == "OK"
        assert srv._monitors
    finally:
        mon.close()
    # Disconnect reclaims the monitor slot (poll: teardown is async).
    deadline = time.monotonic() + 5.0
    while srv._monitors and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not srv._monitors


# -- 3-node subprocess scatter/gather trace (acceptance) --------------------


def _slot_key(prefix, node_idx, n_nodes=3):
    """A key whose CRC16 slot lands in node ``node_idx``'s contiguous
    partition (the supervisor's even split)."""
    from redisson_tpu.cluster.slots import NSLOTS, key_slot

    per = NSLOTS // n_nodes
    lo = node_idx * per
    hi = NSLOTS - 1 if node_idx == n_nodes - 1 else lo + per - 1
    for i in range(100_000):
        k = f"{prefix}-{i}".encode()
        if lo <= key_slot(k) <= hi:
            return k
    raise AssertionError("no key found for node partition")


@pytest.mark.slow
def test_three_node_scatter_gather_yields_one_trace():
    """ISSUE 13 acceptance: a 3-node execute_many under the supervisor
    yields ONE trace whose spans cover client legs, per-node serving
    spans, and device launches, with parent links intact across the
    wire."""
    from redisson_tpu.cluster.supervisor import ClusterSupervisor

    sup = ClusterSupervisor(n_nodes=3).start()
    tracer = Tracer(sample_rate=1.0)
    try:
        client = sup.client(tracer=tracer)
        try:
            keys = [_slot_key("trace", i) for i in range(3)]
            for k in keys:
                r = client.execute("BF.RESERVE", k, "0.01", "1000")
                assert r == b"OK" or r == "OK" or not isinstance(
                    r, Exception
                )
            tracer.reset()  # the batch below is the traced exemplar
            cmds = [["BF.ADD", k, b"item-%d" % i]
                    for i, k in enumerate(keys * 4)]
            replies = client.execute_many(cmds)
            assert all(not isinstance(r, Exception) for r in replies)
            roots = [
                s for s in tracer.spans()
                if s["name"] == "client:execute_many"
            ]
            assert roots, tracer.spans()
            tid = roots[-1]["trace_id"]
            # The per-node rings fill asynchronously (completer threads
            # finish launch spans) — poll briefly.
            merged = {}
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                merged = client.fleet_traces(tid).get(tid, [])
                if (
                    sum(1 for s in merged
                        if s["name"].startswith("leg:")) >= 3
                    and sum(1 for s in merged
                            if s["name"] == "resp:BF.ADD") >= 3
                    and any(s["name"].startswith("launch:")
                            for s in merged)
                ):
                    break
                time.sleep(0.2)
            by_id = {s["span_id"]: s for s in merged}
            root = next(
                s for s in merged if s["name"] == "client:execute_many"
            )
            legs = [s for s in merged if s["name"].startswith("leg:")]
            ingresses = [
                s for s in merged if s["name"] == "resp:BF.ADD"
            ]
            launches = [
                s for s in merged if s["name"].startswith("launch:")
            ]
            assert len(legs) == 3, [s["name"] for s in merged]
            assert len(ingresses) >= 3
            assert launches
            # ONE trace end to end.
            assert {s["trace_id"] for s in merged} == {tid}
            # Parent links intact across the wire: leg -> root,
            # ingress -> its leg, launch -> its ingress.
            leg_ids = {s["span_id"] for s in legs}
            for leg in legs:
                assert leg["parent_id"] == root["span_id"]
            nodes = set()
            for ing in ingresses:
                assert ing["parent_id"] in leg_ids
                nodes.add(ing["attrs"]["node"])
            assert len(nodes) == 3  # one serving span per node
            ingress_ids = {s["span_id"] for s in ingresses}
            for ls in launches:
                assert ls["parent_id"] in ingress_ids
                assert "device_dispatch_us" in ls["attrs"]
        finally:
            client.close()
    finally:
        tracer.set_sample_rate(0.0)
        assert sup.shutdown()
