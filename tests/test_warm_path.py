"""Warm-path dispatch (ISSUE 2 tentpole): AOT bucket pre-compilation,
pinned staging buffers / fused H2D, and the adaptive flush window.

Covers the satellite test checklist:
- pre-warm populates the jit cache for every bucket ≤ max_batch;
- staging buffers are reused across flushes (no per-flush allocation
  growth);
- the adaptive window converges down under sparse traffic and up under
  a burst;
- warm-path results are identical to the host golden engine;
- (slow) no jit compile occurs after pre-warm completes, and warm-path
  submit overhead stays bounded.
"""

import time

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu import Config
from redisson_tpu.codecs import LongCodec
from redisson_tpu.executor import prewarm


def _client(**kw):
    cfg = Config().set_codec(LongCodec()).use_tpu_sketch(
        min_bucket=64, **kw
    )
    return redisson_tpu.create(cfg)


# -- AOT pre-warm -----------------------------------------------------------


def test_prewarm_populates_every_bucket():
    cl = _client(prewarm=True, max_batch=2048)
    try:
        bf = cl.get_bloom_filter("pw-bf")
        bf.try_init(10_000, 0.01)
        assert cl.prewarm_wait(300), "pre-warm did not drain"
        ex = cl._engine.executor
        pw = cl._engine.prewarmer
        ladder = pw.ladder()
        assert ladder[-1] >= 2048 and len(ladder) >= 4
        cached = {k[3] for k in ex._jit_cache if k[0] == "bloom_mixed"}
        assert cached == set(ladder), (sorted(cached), ladder)
        assert pw.errors == 0
    finally:
        cl.shutdown()


def test_prewarm_keyed_ladder_registers_on_first_submit():
    """The codec-shaped (lane count / trim depth) runs-path ladder can't
    be known at pool attach; the FIRST encoded submit schedules it."""
    cl = _client(prewarm=True, max_batch=1024)
    try:
        bf = cl.get_bloom_filter("pw-keyed")
        bf.try_init(10_000, 0.01)
        bf.add_all(np.arange(100, dtype=np.uint64))
        assert cl.prewarm_wait(300)
        ex = cl._engine.executor
        ladder = set(cl._engine.prewarmer.ladder())
        runs = {k[3] for k in ex._jit_cache if k[0] == "bloom_mixk_runs"}
        assert ladder <= runs, (sorted(runs), sorted(ladder))
        assert cl._engine.prewarmer.errors == 0
        # Warm dispatches ran against scratch state: tenant data intact.
        assert bf.contains_all(np.arange(100, dtype=np.uint64)) == 100
        assert bf.contains(np.uint64(999_983)) is False
    finally:
        cl.shutdown()


def test_prewarm_reruns_on_pool_growth():
    """Growth changes the state shape → every jit key; the ladder must
    re-run against the new layout."""
    cl = _client(prewarm=True, max_batch=512,
                 initial_tenants_per_class=2)
    try:
        filters = []
        for i in range(3):  # 3 tenants > capacity 2 -> one growth
            bf = cl.get_bloom_filter(f"grow-{i}")
            bf.try_init(1000, 0.01)
            filters.append(bf)
        assert cl.prewarm_wait(300)
        ex = cl._engine.executor
        pool = cl._engine.registry.lookup("grow-0").pool
        assert pool.generation >= 1
        state_len = pool.state.shape[0]
        cached = {
            k[3] for k in ex._jit_cache
            if k[0] == "bloom_mixed" and k[2] == state_len
        }
        assert cached == set(cl._engine.prewarmer.ladder()), (
            sorted(k for k in ex._jit_cache if k[0] == "bloom_mixed"),
            state_len,
            cl._engine.prewarmer.errors,
        )
    finally:
        cl.shutdown()


def test_prewarm_rebinds_on_change_topology():
    """A live reshard retires the executor: the pre-warmer must adopt
    the successor and re-run its ladders (a stale binding would silently
    skip every warm task while prewarm_wait reported success)."""
    cl = _client(prewarm=True, max_batch=512)
    try:
        bf = cl.get_bloom_filter("rt-bf")
        bf.try_init(1000, 0.01)
        assert cl.prewarm_wait(300)
        assert cl.change_topology(2) is True
        assert cl._engine.prewarmer._executor is cl._engine.executor
        assert cl.prewarm_wait(300)
        assert cl._engine.prewarmer.errors == 0
        bf.add_all(np.arange(50, dtype=np.uint64))
        assert bf.contains_all(np.arange(50, dtype=np.uint64)) == 50
    finally:
        cl.shutdown()


# -- pinned staging ---------------------------------------------------------


def test_staging_buffers_reused_across_flushes():
    """Same-bucket dispatches must land in the SAME host staging buffers
    (ring reuse) — no per-flush allocation growth.  Exercised on the
    executor directly (the rings are thread-local, so the test thread
    dispatching directly observes its own ring)."""
    cl = _client(coalesce=False, exact_add_semantics=True)
    try:
        bf = cl.get_bloom_filter("stage-bf")
        bf.try_init(10_000, 0.01)
        ex = cl._engine.executor
        entry = cl._engine.registry.lookup("stage-bf")
        pool, row = entry.pool, entry.row
        m, k = entry.params["size"], entry.params["hash_iterations"]
        rng = np.random.default_rng(0)

        def dispatch_once():
            B = 64
            rows = np.full(B, row, np.int32)
            m_arr = np.full(B, m, np.uint32)
            h = rng.integers(0, m, B).astype(np.uint32)
            ex.bloom_mixed(pool, rows, m_arr, k, h, h, np.zeros(B, bool))

        for _ in range(10):  # > ring depth: every slot allocated
            dispatch_once()
        rings = ex._staging.rings  # this thread's rings
        key = ("bloom_mixed", ex._bucket(64))
        assert key in rings
        bufs_before = {id(s.buf) for s in rings[key][1]}
        assert len(bufs_before) <= 8  # bounded by ring depth
        for _ in range(40):  # 5x ring depth more flushes
            dispatch_once()
        bufs_after = {id(s.buf) for s in rings[key][1]}
        # Same-bucket flushes cycled the SAME buffers — zero new
        # allocations after the ring filled.
        assert bufs_after == bufs_before
    finally:
        cl.shutdown()


def test_fused_block_roundtrip_preserves_results():
    """The packed-block encode (host) and slice/bitcast decode (device)
    must be lossless: interleaved add/contains with duplicate keys via
    the fused kernels matches the golden host engine exactly."""
    # Default codec (string keys ride the encoded device-hash path).
    tpu = redisson_tpu.create(
        Config().use_tpu_sketch(min_bucket=64, batch_window_us=500)
    )
    host = redisson_tpu.create(Config())
    try:
        rng = np.random.default_rng(42)
        a = tpu.get_bloom_filter("diff-bf")
        b = host.get_bloom_filter("diff-bf")
        for f in (a, b):
            f.try_init(5000, 0.01)
        for _ in range(8):
            keys = rng.integers(0, 3000, 257).astype(np.uint64)
            assert a.add_all(keys) == b.add_all(keys)
            probe = rng.integers(0, 6000, 511).astype(np.uint64)
            np.testing.assert_array_equal(
                a.contains_each(probe), b.contains_each(probe)
            )
        # HLL + bitset + CMS through their fused coalesced kernels.
        ha, hb = tpu.get_hyper_log_log("diff-h"), host.get_hyper_log_log("diff-h")
        ha.add_all(np.arange(5000, dtype=np.uint64))
        hb.add_all(np.arange(5000, dtype=np.uint64))
        assert ha.count() == hb.count()
        sa, sb = tpu.get_bit_set("diff-bs"), host.get_bit_set("diff-bs")
        idx = rng.integers(0, 4096, 300).astype(np.uint32)
        np.testing.assert_array_equal(sa.set_many(idx), sb.set_many(idx))
        np.testing.assert_array_equal(
            sa.get_many(np.arange(4096, dtype=np.uint32)),
            sb.get_many(np.arange(4096, dtype=np.uint32)),
        )
        ca, cb = (
            tpu.get_count_min_sketch("diff-c"),
            host.get_count_min_sketch("diff-c"),
        )
        ca.try_init(4, 1 << 10)
        cb.try_init(4, 1 << 10)
        ca.add_all(["hot"] * 100 + ["cold"] * 3)
        cb.add_all(["hot"] * 100 + ["cold"] * 3)
        assert ca.estimate("hot") == cb.estimate("hot")
        assert ca.estimate("cold") == cb.estimate("cold")
    finally:
        tpu.shutdown()
        host.shutdown()


def test_many_run_segment_takes_array_path_and_stays_correct():
    """>1024 runs in one coalesced segment expand to per-op arrays (the
    runs kernel's Cp compile space stays the single pre-warmed 1024
    bucket) — results must be unchanged."""
    cl = _client(batch_window_us=200_000, max_batch=1 << 18)
    try:
        bf = cl.get_bloom_filter("runs-bf")
        bf.try_init(10_000, 0.01)
        # 1200 single-key submits join ONE segment (long window, same
        # pool) -> 1200 runs at flush.
        futs = [bf.add_async(np.uint64(i % 700)) for i in range(1200)]
        results = [f.result() for f in futs]
        assert sum(results) == 700  # duplicates report False, exactly
        ex = cl._engine.executor
        # No runs-kernel key beyond Cp=1024 was compiled.
        assert not any(
            k[0] == "bloom_mixk_runs" and k[7] > 1024
            for k in ex._jit_cache
        ), [k for k in ex._jit_cache if k[0] == "bloom_mixk_runs"]
        assert bf.contains_all(np.arange(700, dtype=np.uint64)) == 700
    finally:
        cl.shutdown()


# -- adaptive flush window --------------------------------------------------


def _bare_coalescer(**kw):
    from redisson_tpu.executor.coalescer import BatchCoalescer

    class _Lazy:
        def __init__(self, v):
            self._v = v

        def result(self):
            return self._v

    c = BatchCoalescer(
        batch_window_us=kw.pop("batch_window_us", 1000),
        max_batch=kw.pop("max_batch", 4096),
        **kw,
    )
    return c, lambda cols: _Lazy(np.concatenate(cols))


def test_adaptive_window_converges_down_when_sparse():
    c, dispatch = _bare_coalescer(batch_window_us=1000)
    try:
        arr = np.arange(4, dtype=np.int64)
        for _ in range(30):  # sparse trickle: a few ops, long gaps
            c.submit(("op",), dispatch, (arr,), 4).result(10)
            time.sleep(0.01)
        assert c.window_s <= c.base_window_s, (c.window_s, c.base_window_s)
        assert c.window_s == pytest.approx(c.min_window_s, rel=0.5)
    finally:
        c.shutdown()


def test_adaptive_window_grows_under_burst():
    c, dispatch = _bare_coalescer(batch_window_us=1000, max_batch=1 << 20)
    try:
        arr = np.arange(4096, dtype=np.int64)
        futs = [
            c.submit(("op",), dispatch, (arr,), 4096) for _ in range(200)
        ]
        deadline = time.monotonic() + 5.0
        while c.window_s < c.max_window_s * 0.5 and time.monotonic() < deadline:
            futs.append(c.submit(("op",), dispatch, (arr,), 4096))
            if len(futs) > 400:
                for f in futs[:200]:
                    f.result(10)
                del futs[:200]
        assert c.window_s > c.base_window_s, (c.window_s, c.base_window_s)
        for f in futs:
            f.result(10)
    finally:
        c.shutdown()


def test_adaptive_window_stays_inside_bounds_and_can_disable():
    c, _ = _bare_coalescer(
        batch_window_us=1000, min_window_us=200, max_window_us=5000
    )
    try:
        assert c.min_window_s == pytest.approx(200e-6)
        assert c.max_window_s == pytest.approx(5000e-6)
    finally:
        c.shutdown()
    c2, dispatch = _bare_coalescer(batch_window_us=777, adaptive_window=False)
    try:
        arr = np.arange(8, dtype=np.int64)
        for _ in range(5):
            c2.submit(("op",), dispatch, (arr,), 8).result(10)
        assert c2.window_s == pytest.approx(777e-6)  # fixed when disabled
    finally:
        c2.shutdown()


# -- slow guards ------------------------------------------------------------


@pytest.mark.slow
def test_no_compile_after_prewarm_completes():
    """The acceptance teeth: once pre-warm drains, a serving-shaped
    workload over every bucket of the ladder triggers ZERO XLA backend
    compiles (counted via the jax.monitoring hook)."""
    cl = _client(prewarm=True, max_batch=2048, batch_window_us=200)
    try:
        bf = cl.get_bloom_filter("nc-bf")
        bf.try_init(10_000, 0.01)
        # First encoded submit reveals the codec signature (pays its own
        # compile) and schedules the keyed ladders.
        bf.add_all(np.arange(64, dtype=np.uint64))
        assert cl.prewarm_wait(600)
        before = prewarm.compile_count()
        rng = np.random.default_rng(1)
        for nops in (1, 33, 64, 100, 128, 500, 1024, 2000):
            keys = rng.integers(0, 20_000, nops).astype(np.uint64)
            bf.add_all(keys)
            bf.contains_all(keys)
        cl._engine._drain()
        after = prewarm.compile_count()
        assert after == before, f"{after - before} compiles on warm path"
    finally:
        cl.shutdown()


@pytest.mark.slow
def test_warm_path_submit_overhead_bounded():
    """Warm-path producer overhead guard: with every kernel pre-warmed,
    the mean async submit cost for a 256-op chunk stays well under a
    millisecond (paired-minimum measurement, tolerant of shared-box
    noise)."""
    cl = _client(prewarm=True, max_batch=1 << 16, batch_window_us=500)
    try:
        bf = cl.get_bloom_filter("ov-bf")
        bf.try_init(100_000, 0.01)
        bf.add_all(np.arange(256, dtype=np.uint64))
        assert cl.prewarm_wait(600)
        rng = np.random.default_rng(2)
        chunks = [
            rng.integers(0, 80_000, 256).astype(np.uint64) for _ in range(64)
        ]
        best = float("inf")
        for _ in range(5):
            futs = []
            t0 = time.perf_counter()
            for ch in chunks:
                futs.append(bf.add_all_async(ch))
            dt = (time.perf_counter() - t0) / len(chunks)
            best = min(best, dt)
            for f in futs:
                f.result()
        assert best < 2e-3, f"warm submit cost {best * 1e3:.2f} ms/chunk"
    finally:
        cl.shutdown()
