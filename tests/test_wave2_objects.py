"""Catalog wave 2: multimaps, RLocalCachedMap, RStream, RReliableTopic
(VERDICT r2 Next #8 — per-family test classes like test_grid_objects.py).
"""

import threading
import time

import pytest

import redisson_tpu
from redisson_tpu import Config


@pytest.fixture
def client():
    c = redisson_tpu.create(Config())
    yield c
    c.shutdown()


class TestListMultimap:
    def test_put_get_duplicates(self, client):
        mm = client.get_list_multimap("lmm")
        assert mm.put("k", "a")
        assert mm.put("k", "a")  # duplicates allowed
        assert mm.put("k", "b")
        assert mm.get_all("k") == ["a", "a", "b"]
        assert mm.size() == 3
        assert mm.key_size() == 1

    def test_remove_one_occurrence(self, client):
        mm = client.get_list_multimap("lmm2")
        mm.put_all("k", ["a", "a", "b"])
        assert mm.remove("k", "a")
        assert mm.get_all("k") == ["a", "b"]
        assert not mm.remove("k", "zzz")

    def test_remove_all_and_fast_remove(self, client):
        mm = client.get_list_multimap("lmm3")
        mm.put_all("k1", ["a", "b"])
        mm.put_all("k2", ["c"])
        assert mm.remove_all("k1") == ["a", "b"]
        assert not mm.contains_key("k1")
        assert mm.fast_remove("k2", "missing") == 1

    def test_entries_values_keyset(self, client):
        mm = client.get_list_multimap("lmm4")
        mm.put("x", 1)
        mm.put("y", 2)
        assert sorted(mm.key_set()) == ["x", "y"]
        assert sorted(mm.values()) == [1, 2]
        assert sorted(mm.entries()) == [("x", 1), ("y", 2)]


class TestSetMultimap:
    def test_distinct_values(self, client):
        mm = client.get_set_multimap("smm")
        assert mm.put("k", "a")
        assert not mm.put("k", "a")  # set semantics
        assert mm.put("k", "b")
        assert sorted(mm.get_all("k")) == ["a", "b"]
        assert mm.contains_entry("k", "a")
        assert not mm.contains_entry("k", "zzz")
        assert mm.contains_value("b")


class TestMultimapCache:
    def test_per_key_ttl(self, client):
        mm = client.get_set_multimap_cache("smmc")
        mm.put("hot", 1)
        mm.put("cold", 2)
        assert mm.expire_key("cold", 0.1)
        assert mm.remain_key_ttl_ms("cold") > 0
        assert mm.remain_key_ttl_ms("hot") == -1
        assert mm.remain_key_ttl_ms("absent") == -2
        time.sleep(0.15)
        assert not mm.contains_key("cold")
        assert mm.contains_key("hot")


class TestLocalCachedMap:
    def test_near_cache_hit(self, client):
        m = client.get_local_cached_map("lcm")
        m.put("a", 1)
        assert m.get("a") == 1
        assert m.cached_size() >= 1
        # Reads served from the near cache even if backing entry mutates
        # underneath without invalidation (direct Map handle):
        raw = client.get_map("lcm")
        raw.fast_put("a", 99)
        assert m.get("a") == 1  # stale by design until invalidated

    def test_invalidation_between_handles(self, client):
        m1 = client.get_local_cached_map("lcm2")
        m2 = client.get_local_cached_map("lcm2")
        m1.put("k", "v1")
        assert m2.get("k") == "v1"  # m2 caches it
        m1.put("k", "v2")  # publishes invalidation
        client._topic_bus.drain()
        assert m2.get("k") == "v2"  # m2's cache entry was dropped

    def test_update_strategy_pushes_value(self, client):
        from redisson_tpu.grid.local_cached_map import UPDATE

        m1 = client.get_local_cached_map("lcm3", sync_strategy=UPDATE)
        m2 = client.get_local_cached_map("lcm3", sync_strategy=UPDATE)
        m1.put("k", "v1")
        client._topic_bus.drain()
        # m2 received the VALUE without ever reading the backing map.
        assert m2.cached_size() == 1
        assert m2.get("k") == "v1"

    def test_writer_keeps_own_cache(self, client):
        m = client.get_local_cached_map("lcm4")
        m.put("k", "v")
        client._topic_bus.drain()
        assert m.cached_size() == 1  # own write didn't self-invalidate

    def test_lru_bound(self, client):
        m = client.get_local_cached_map("lcm5", cache_size=4)
        for i in range(10):
            m.put(f"k{i}", i)
        assert m.cached_size() <= 4


class TestStream:
    def test_add_range_read(self, client):
        s = client.get_stream("st")
        id1 = s.add({"f": "v1"})
        id2 = s.add({"f": "v2"})
        assert s.size() == 2
        entries = s.range()
        assert [i for i, _ in entries] == [id1, id2]
        assert entries[0][1] == {"f": "v1"}
        assert s.rev_range()[0][0] == id2
        assert [i for i, _ in s.read(from_id=id1)] == [id2]
        assert s.get(id1) == {"f": "v1"}
        assert s.last_id() == id2

    def test_explicit_ids_and_ordering(self, client):
        s = client.get_stream("st2")
        s.add({"a": 1}, id="5-1")
        with pytest.raises(ValueError):
            s.add({"a": 2}, id="5-1")  # not greater than last
        s.add({"a": 2}, id="5-2")
        assert [i for i, _ in s.range()] == ["5-1", "5-2"]

    def test_trim_and_delete(self, client):
        s = client.get_stream("st3")
        ids = [s.add({"n": i}) for i in range(10)]
        assert s.remove(ids[3]) == 1
        assert s.size() == 9
        assert s.trim(5) == 4
        assert s.size() == 5

    def test_maxlen_on_add(self, client):
        s = client.get_stream("st4")
        for i in range(10):
            s.add({"n": i}, maxlen=3)
        assert s.size() == 3

    def test_consumer_groups_deliver_and_ack(self, client):
        s = client.get_stream("grp")
        s.create_group("g1", from_id="0-0")
        ids = [s.add({"n": i}) for i in range(5)]
        got1 = s.read_group("g1", "c1", count=3)
        assert [i for i, _ in got1] == ids[:3]
        got2 = s.read_group("g1", "c2")
        assert [i for i, _ in got2] == ids[3:]
        # Pending before ack
        p = s.pending("g1")
        assert p["total"] == 5
        assert p["consumers"] == {"c1": 3, "c2": 2}
        assert s.ack("g1", *[i for i, _ in got1]) == 3
        assert s.pending("g1")["total"] == 2
        # Re-read own pending (explicit id, not ">")
        own = s.read_group("g1", "c2", ids="0-0")
        assert [i for i, _ in own] == ids[3:]

    def test_group_from_dollar_sees_only_new(self, client):
        s = client.get_stream("grp2")
        s.add({"n": "old"})
        s.create_group("g", from_id="$")
        assert s.read_group("g", "c") == []
        nid = s.add({"n": "new"})
        assert [i for i, _ in s.read_group("g", "c")] == [nid]

    def test_claim_idle_entries(self, client):
        s = client.get_stream("grp3")
        s.create_group("g", from_id="0-0")
        mid = s.add({"n": 1})
        s.read_group("g", "dead-consumer")
        time.sleep(0.05)
        claimed = s.claim("g", "rescuer", 10, mid)
        assert [i for i, _ in claimed] == [mid]
        pr = s.pending_range("g")
        assert pr[0]["consumer"] == "rescuer"
        assert pr[0]["delivered"] == 2
        # min_idle not reached -> no claim
        assert s.claim("g", "again", 60_000, mid) == []

    def test_auto_claim(self, client):
        s = client.get_stream("grp4")
        s.create_group("g", from_id="0-0")
        ids = [s.add({"n": i}) for i in range(4)]
        s.read_group("g", "dead")
        time.sleep(0.05)
        claimed = s.auto_claim("g", "live", 10, count=3)
        assert [i for i, _ in claimed] == ids[:3]

    def test_busygroup_and_nogroup(self, client):
        s = client.get_stream("grp5")
        s.create_group("g")
        with pytest.raises(ValueError, match="BUSYGROUP"):
            s.create_group("g")
        with pytest.raises(ValueError, match="NOGROUP"):
            s.read_group("missing", "c")
        assert s.remove_group("g")
        assert not s.remove_group("g")

    def test_blocking_read(self, client):
        s = client.get_stream("blk")
        got = []

        def reader():
            got.extend(s.read(from_id="$", block_seconds=5.0))

        t = threading.Thread(target=reader)
        t.start()
        time.sleep(0.1)
        s.add({"x": 42})
        t.join(timeout=5)
        assert not t.is_alive()
        assert got and got[0][1] == {"x": 42}

    def test_xinfo(self, client):
        s = client.get_stream("info")
        s.create_group("g")
        s.add({"a": 1})
        s.read_group("g", "c1")
        groups = s.list_groups()
        assert groups[0]["name"] == "g"
        assert groups[0]["pending"] == 1
        cons = s.list_consumers("g")
        assert cons == [{"name": "c1", "pending": 1}]


class TestReliableTopic:
    def test_at_least_once_delivery(self, client):
        rt = client.get_reliable_topic("rel")
        rt.publish("before-subscribe")  # no listener yet: not replayed
        got = []
        rt.add_listener(lambda ch, msg: got.append(msg))
        rt.publish("m1")
        rt.publish("m2")
        deadline = time.time() + 5
        while time.time() < deadline and len(got) < 2:
            time.sleep(0.02)
        assert got == ["m1", "m2"]
        assert rt.count_listeners() == 1

    def test_two_listeners_both_receive(self, client):
        rt = client.get_reliable_topic("rel2")
        a, b = [], []
        rt.add_listener(lambda ch, m: a.append(m))
        rt.add_listener(lambda ch, m: b.append(m))
        rt.publish("x")
        deadline = time.time() + 5
        while time.time() < deadline and (len(a) < 1 or len(b) < 1):
            time.sleep(0.02)
        assert a == ["x"] and b == ["x"]
