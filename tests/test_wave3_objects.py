"""Catalog wave 3: Geo, TimeSeries, TransferQueue, PriorityBlocking/Deque,
JCache, SCAN iterators."""

import threading
import time

import pytest

import redisson_tpu
from redisson_tpu import Config


@pytest.fixture
def client():
    c = redisson_tpu.create(Config())
    yield c
    c.shutdown()


class TestGeo:
    def test_add_pos_dist(self, client):
        g = client.get_geo("geo")
        assert g.add(13.361389, 38.115556, "Palermo") == 1
        assert g.add(15.087269, 37.502669, "Catania") == 1
        assert g.add(13.361389, 38.115556, "Palermo") == 0  # update
        d = g.dist("Palermo", "Catania", "km")
        assert d is not None and 160 < d < 172  # Redis reports ~166.27 km
        pos = g.pos("Palermo", "ghost")
        assert "Palermo" in pos and "ghost" not in pos

    def test_search_radius(self, client):
        g = client.get_geo("geo2")
        g.add(13.361389, 38.115556, "Palermo")
        g.add(15.087269, 37.502669, "Catania")
        got = g.search_radius(15, 37, 200, "km")
        assert got == ["Catania", "Palermo"]  # nearest first
        near = g.search_radius(15, 37, 100, "km")
        assert near == ["Catania"]
        with_d = g.search_radius_from_member("Palermo", 200, "km", with_dist=True)
        assert with_d[0][0] == "Palermo" and with_d[0][1] < 1e-6

    def test_geohash(self, client):
        g = client.get_geo("geo3")
        g.add(13.361389, 38.115556, "Palermo")
        h = g.hash("Palermo")["Palermo"]
        assert h.startswith("sqc8b49rny")  # Redis's GEOHASH prefix

    def test_coordinate_validation(self, client):
        g = client.get_geo("geo4")
        with pytest.raises(ValueError):
            g.add(200.0, 0.0, "bad")


class TestTimeSeries:
    def test_add_get_range(self, client):
        ts = client.get_time_series("ts")
        for t in (30, 10, 20):
            ts.add(t, f"v{t}")
        assert ts.size() == 3
        assert ts.get(20) == "v20"
        assert ts.range(10, 25) == [(10, "v10"), (20, "v20")]
        assert ts.range_reversed(0, 100)[0] == (30, "v30")
        assert ts.first() == ["v10"]
        assert ts.last() == ["v30"]
        assert ts.first_timestamp() == 10
        assert ts.last_timestamp() == 30

    def test_same_timestamp_replaces(self, client):
        ts = client.get_time_series("ts2")
        ts.add(5, "old")
        ts.add(5, "new")
        assert ts.size() == 1
        assert ts.get(5) == "new"

    def test_poll_and_remove_range(self, client):
        ts = client.get_time_series("ts3")
        for t in range(5):
            ts.add(t, t)
        assert ts.poll_first() == [0]
        assert ts.poll_last(2) == [4, 3]
        assert ts.remove_range(1, 2) == 2
        assert ts.size() == 0

    def test_entry_ttl(self, client):
        ts = client.get_time_series("ts4")
        ts.add(1, "stays")
        ts.add(2, "goes", ttl_seconds=0.1)
        time.sleep(0.15)
        assert ts.size() == 1
        assert ts.get(2) is None

    def test_labels(self, client):
        ts = client.get_time_series("ts5")
        ts.add(1, "v", label="L")
        assert ts.entry_range(0, 10) == [(1, "v", "L")]


class TestTransferQueue:
    def test_transfer_blocks_until_taken(self, client):
        q = client.get_transfer_queue("tq")
        done = []

        def producer():
            done.append(q.transfer("hot-potato", timeout_seconds=5.0))

        t = threading.Thread(target=producer)
        t.start()
        time.sleep(0.1)
        assert not done  # still blocked: nobody took it
        assert q.take() == "hot-potato"
        t.join(timeout=5)
        assert done == [True]

    def test_transfer_timeout_withdraws(self, client):
        q = client.get_transfer_queue("tq2")
        assert q.transfer("x", timeout_seconds=0.1) is False
        assert q.poll() is None  # withdrawn, not left behind

    def test_try_transfer_needs_waiting_consumer(self, client):
        q = client.get_transfer_queue("tq3")
        assert q.try_transfer("x") is False
        got = []
        t = threading.Thread(target=lambda: got.append(q.poll(2.0)))
        t.start()
        time.sleep(0.1)
        assert q.has_waiting_consumer()
        assert q.try_transfer("y") is True
        t.join(timeout=5)
        assert got == ["y"]


class TestPriorityVariants:
    def test_priority_blocking_take(self, client):
        q = client.get_priority_blocking_queue("pbq")
        got = []
        t = threading.Thread(target=lambda: got.append(q.take()))
        t.start()
        time.sleep(0.05)
        q.offer(5)
        t.join(timeout=5)
        assert got == [5]
        q.offer(3)
        q.offer(9)
        assert q.poll(1.0) == 3  # natural order

    def test_priority_deque_both_ends(self, client):
        d = client.get_priority_deque("pdq")
        for v in (5, 1, 9, 3):
            d.offer(v)
        assert d.peek_first() == 1
        assert d.peek_last() == 9
        assert d.poll_first() == 1
        assert d.poll_last() == 9
        assert d.read_all() == [3, 5]


class TestJCache:
    def test_jsr107_contracts(self, client):
        cache = client.get_jcache("jc")
        assert cache.put("k", "v") is None
        assert cache.get("k") == "v"
        assert cache.get_and_put("k", "v2") == "v"
        assert cache.put_if_absent("k", "x") is False
        assert cache.put_if_absent("new", "n") is True
        assert cache.contains_key("k")
        assert cache.remove("missing") is False
        assert cache.remove("k") is True
        assert cache.get_and_remove("new") == "n"
        assert not cache.contains_key("new")

    def test_remove_with_old_value(self, client):
        cache = client.get_jcache("jc2")
        cache.put("k", "v")
        assert cache.remove("k", "wrong") is False
        assert cache.remove("k", "v") is True

    def test_default_ttl(self, client):
        cache = client.get_jcache("jc3", default_ttl_seconds=0.1)
        cache.put("k", "v")
        assert cache.get("k") == "v"
        time.sleep(0.15)
        assert cache.get("k") is None

    def test_cache_manager(self, client):
        mgr = client.get_cache_manager()
        c1 = mgr.create_cache("m1")
        assert mgr.get_cache("m1") is c1
        c1.put("k", 1)
        mgr.destroy_cache("m1")
        assert "m1" not in mgr.get_cache_names()


class TestScanIterators:
    def test_keys_scan(self, client):
        for i in range(25):
            client.get_bucket(f"scan:{i}").set(i)
        got = list(client.get_keys().scan_iterator("scan:*", count=7))
        assert sorted(got) == sorted(f"scan:{i}" for i in range(25))
        assert len(got) == len(set(got))  # exactly once

    def test_map_hscan(self, client):
        m = client.get_map("hm")
        for i in range(15):
            m.put(f"k{i}", i)
        keys = list(m.key_iterator(count=4))
        assert sorted(keys) == sorted(f"k{i}" for i in range(15))
        entries = dict(m.entry_iterator(count=4))
        assert entries["k3"] == 3
