"""Catalog wave 4: map entry listeners, ShardedTopic, JsonBucket,
NodesGroup admin."""

import time

import pytest

import redisson_tpu
from redisson_tpu import Config


@pytest.fixture
def client():
    c = redisson_tpu.create(Config().use_tpu_sketch(min_bucket=64))
    yield c
    c.shutdown()


class TestMapEntryListeners:
    def test_created_updated_removed_events(self, client):
        m = client.get_map("lm")
        events = []
        m.add_listener(lambda ev, k, v: events.append((ev, k, v)))
        m.put("k", 1)       # created
        m.put("k", 2)       # updated
        m.remove("k")       # removed
        client._topic_bus.drain()
        assert events == [
            ("created", "k", 1), ("updated", "k", 2), ("removed", "k", 2),
        ]

    def test_event_filter_and_remove_listener(self, client):
        m = client.get_map("lm2")
        created = []
        lid = m.add_listener(lambda ev, k, v: created.append(k), event="created")
        m.put("a", 1)
        m.put("a", 2)  # update: filtered out
        client._topic_bus.drain()
        assert created == ["a"]
        m.remove_listener(lid)
        m.put("b", 1)
        client._topic_bus.drain()
        assert created == ["a"]

    def test_mapcache_puts_emit(self, client):
        mc = client.get_map_cache("lmc")
        events = []
        mc.add_listener(lambda ev, k, v: events.append(ev))
        mc.put("k", 1, ttl_seconds=30)
        mc.fast_put("k", 2)
        client._topic_bus.drain()
        assert events == ["created", "updated"]


class TestShardedTopic:
    def test_publish_subscribe(self, client):
        t = client.get_sharded_topic("st")
        got = []
        t.add_listener(lambda ch, m: got.append(m))
        assert t.publish("msg") == 1
        client._topic_bus.drain()
        assert got == ["msg"]


class TestJsonBucket:
    def test_root_and_paths(self, client):
        jb = client.get_json_bucket("doc")
        jb.set({"user": {"name": "ada", "tags": ["a"], "visits": 1}})
        assert jb.get_path("user.name") == "ada"
        jb.set_path("user.name", "grace")
        assert jb.get_path("user.name") == "grace"
        assert jb.array_append("user.tags", "b", "c") == 3
        assert jb.get_path("user.tags") == ["a", "b", "c"]
        assert jb.increment("user.visits", 5) == 6
        assert jb.string_append("user.name", "!") == 6
        assert jb.get_path("$")["user"]["name"] == "grace!"

    def test_array_index_paths(self, client):
        jb = client.get_json_bucket("doc2")
        jb.set({"xs": [{"v": 1}, {"v": 2}]})
        assert jb.get_path("xs.1.v") == 2
        jb.set_path("xs.0.v", 10)
        assert jb.get_path("xs.0.v") == 10


class TestNodesGroup:
    def test_ping_and_info(self, client):
        ng = client.get_nodes_group()
        nodes = ng.get_nodes()
        assert nodes, "at least one device node"
        assert ng.ping_all()
        info = nodes[0].info()
        assert "platform" in info and "id" in info
        assert nodes[0].time() > 0

    def test_sharded_mesh_lists_all_shards(self):
        c = redisson_tpu.create(
            Config().use_tpu_sketch(num_shards=8, min_bucket=64)
        )
        try:
            nodes = c.get_nodes_group().get_nodes()
            assert len(nodes) == 8
            assert [n.shard for n in nodes] == list(range(8))
        finally:
            c.shutdown()


class TestProfiler:
    def test_trace_capture(self, client, tmp_path):
        import os

        prof = client.get_profiler()
        with prof.trace(str(tmp_path)):
            bf = client.get_bloom_filter("prof-bf")
            bf.try_init(1000, 0.01)
            bf.add_all([1, 2, 3])
            with prof.annotate("probe"):
                bf.contains(1)
        # A trace directory with at least one artifact was produced.
        found = [
            os.path.join(r, f)
            for r, _, fs in os.walk(tmp_path)
            for f in fs
        ]
        assert found, "profiler produced no trace files"
        assert isinstance(prof.device_memory(), dict)

    def test_double_start_raises(self, client, tmp_path):
        import pytest as _pytest

        prof = client.get_profiler()
        prof.start(str(tmp_path))
        try:
            with _pytest.raises(RuntimeError):
                prof.start(str(tmp_path))
        finally:
            prof.stop()
