"""Lock-order witness (ISSUE 8): cycle detection across real threads,
blocking-under-lock probes, the allow_blocking escape hatch, and the
zero-overhead-when-disabled contract.
"""

import threading
import time

import pytest

from redisson_tpu.analysis import witness


@pytest.fixture
def forced_witness():
    witness.force(True)
    witness.reset()
    yield
    witness.take_violations()
    witness.reset()
    witness.force(False)


def test_disabled_named_is_identity():
    if witness.enabled():
        pytest.skip("witness armed via RTPU_LOCK_WITNESS")
    lock = threading.Lock()
    assert witness.named(lock, "x") is lock


def test_two_lock_cycle_across_threads_is_reported(forced_witness):
    """The tentpole contract: a REAL two-lock cycle built by two
    threads acquiring in opposite orders is reported as a potential
    deadlock, with the offending stack pair — even though this run
    never actually deadlocks (the orders execute sequentially)."""
    a = witness.named(threading.Lock(), "w.A")
    b = witness.named(threading.Lock(), "w.B")

    def a_then_b():
        with a:
            with b:
                pass

    def b_then_a():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=a_then_b)
    t1.start()
    t1.join()
    assert witness.take_violations() == []  # one order alone: no cycle
    t2 = threading.Thread(target=b_then_a)
    t2.start()
    t2.join()
    vs = witness.take_violations()
    assert [v.kind for v in vs] == ["cycle"]
    assert "w.A" in vs[0].message and "w.B" in vs[0].message
    # The offending stack PAIR rides the report: this acquisition and
    # the recorded opposite-order edge.
    assert len(vs[0].stacks) >= 2
    assert any(s for _, s in vs[0].stacks)


def test_sleep_under_named_lock_is_reported(forced_witness):
    lk = witness.named(threading.Lock(), "w.blk")
    with lk:
        time.sleep(0.001)
    vs = witness.take_violations()
    assert [v.kind for v in vs] == ["blocking"]
    assert "time.sleep" in vs[0].message and "w.blk" in vs[0].message


def test_future_result_under_named_lock_is_reported(forced_witness):
    from concurrent.futures import Future

    fut = Future()
    fut.set_result(42)
    lk = witness.named(threading.Lock(), "w.fut")
    with lk:
        assert fut.result() == 42
    vs = witness.take_violations()
    assert [v.kind for v in vs] == ["blocking"]
    assert "Future.result" in vs[0].message


def test_allow_blocking_scope_suppresses_with_reason(forced_witness):
    lk = witness.named(threading.Lock(), "w.allow")
    with lk:
        with witness.allow_blocking("fixture: documented by-design"):
            time.sleep(0.001)
    assert witness.take_violations() == []
    with pytest.raises(ValueError):
        witness.allow_blocking("")


def test_no_blocking_report_when_nothing_held(forced_witness):
    witness.named(threading.Lock(), "w.idle")  # probes installed
    time.sleep(0.001)
    assert witness.take_violations() == []


def test_condition_wait_releases_held_bookkeeping(forced_witness):
    """Condition.wait() releases the underlying lock: its wait must not
    count as blocking-under-lock, and the lock must show held again
    after wake."""
    lk = witness.named(threading.Lock(), "w.cv")
    cv = threading.Condition(lk)
    with cv:
        cv.wait(timeout=0.01)
    assert witness.take_violations() == []


def test_rlock_reentrancy_no_self_edge(forced_witness):
    rl = witness.named(threading.RLock(), "w.rl")
    with rl:
        with rl:
            pass
    assert witness.take_violations() == []


def test_consistent_order_never_reports(forced_witness):
    a = witness.named(threading.Lock(), "w.ord.A")
    b = witness.named(threading.Lock(), "w.ord.B")
    for _ in range(3):
        with a:
            with b:
                pass
    assert witness.take_violations() == []


def test_three_lock_cycle_detected(forced_witness):
    a = witness.named(threading.Lock(), "w3.A")
    b = witness.named(threading.Lock(), "w3.B")
    c = witness.named(threading.Lock(), "w3.C")

    def run(first, second):
        with first:
            with second:
                pass

    for first, second in ((a, b), (b, c)):
        t = threading.Thread(target=run, args=(first, second))
        t.start()
        t.join()
    assert witness.take_violations() == []
    t = threading.Thread(target=run, args=(c, a))
    t.start()
    t.join()
    vs = witness.take_violations()
    assert [v.kind for v in vs] == ["cycle"]
    for name in ("w3.A", "w3.B", "w3.C"):
        assert name in vs[0].message


def test_engine_paths_run_clean_under_witness(forced_witness):
    """The wired locks (coalescer/engines/nearcache/tenancy) hold the
    witness discipline on the real serving path: submit, flush, read,
    degraded-free ops — zero cycles, zero blocking-under-lock."""
    import numpy as np

    from redisson_tpu import Config
    from redisson_tpu.client import RedissonTpuClient

    client = RedissonTpuClient(
        Config().use_tpu_sketch(batch_window_us=100, min_bucket=64)
    )
    try:
        bf = client.get_bloom_filter("witness-e2e")
        bf.try_init(10_000, 0.01)
        keys = np.arange(64, dtype=np.uint64)
        bf.add_all(keys)
        assert bf.contains_all(keys) == len(keys)
        assert client._engine.delete("witness-e2e") is True
    finally:
        client.shutdown()
    vs = witness.take_violations()
    assert vs == [], "\n\n".join(v.format() for v in vs)
